"""Tier-1 enforcement of the raylint invariant checker (ISSUE 7).

Four layers, mirroring the tentpole's contract:

1. **Tree gate** — ``ray_tpu/`` must lint clean against the checked-in
   baseline: zero unsuppressed violations, zero parse errors, no stale
   baseline entries (the baseline may only shrink), under the 30 s
   tier-1 runtime budget.
2. **Historical-bug regressions** — the frozen fixtures in
   ``raylint_fixtures/`` reproduce the MemoryStore ``__del__``→Lock
   deadlock (R1, PR 5) and the leaked read-loop task (R4, PRs 1/3);
   each must trip its rule exactly on the ``# expect-Rn`` lines.
3. **Engine semantics** — inline ``# raylint: disable`` suppression,
   baseline grandfathering/growth/stale accounting, JSON output and
   exit codes.
4. **R5's dynamic half** — every public exception class in
   ``ray_tpu.exceptions`` is auto-instantiated with synthesized field
   values and must survive a pickle round-trip with type, fields
   (including nested ``DeathContext``), ``args`` and ``str()`` intact.
"""

import asyncio
import inspect
import json
import os
import pickle
import warnings

import pytest

import ray_tpu.exceptions as exc_mod
from ray_tpu.devtools.lint import baseline as baseline_mod
from ray_tpu.devtools.lint.cli import main as lint_main
from ray_tpu.devtools.lint.engine import default_baseline_path, run_lint

TESTS_DIR = os.path.dirname(os.path.abspath(__file__))
REPO_ROOT = os.path.dirname(TESTS_DIR)
FIXTURES = os.path.join(TESTS_DIR, "raylint_fixtures")

# The tier-1 runtime budget from ISSUE 7: the whole-tree scan (parse +
# call graph + all 8 rules) must stay well under the tier's patience.
LINT_BUDGET_S = 30.0


# ---------------------------------------------------------------------------
# 1. Tree gate
# ---------------------------------------------------------------------------
class TestTreeGate:
    @pytest.fixture(scope="class")
    def tree_result(self):
        return run_lint([os.path.join(REPO_ROOT, "ray_tpu")],
                        project_root=REPO_ROOT,
                        baseline_path=default_baseline_path())

    def test_no_unsuppressed_violations(self, tree_result):
        assert not tree_result.parse_errors, tree_result.parse_errors
        assert not tree_result.violations, (
            "raylint found unsuppressed violations — fix them, or add an "
            "inline '# raylint: disable=Rn -- reason' with justification:\n"
            + "\n".join(v.format() for v in tree_result.violations))

    def test_baseline_only_shrinks(self, tree_result):
        # A stale entry means a grandfathered violation was fixed but the
        # baseline still carries budget for it: shrink the file. Growth is
        # impossible by construction (a violation over budget fails above).
        assert not tree_result.stale_baseline, (
            "baseline entries no longer match any violation — shrink "
            "baseline.json (python -m ray_tpu.devtools.lint ray_tpu "
            "--update-baseline): " + ", ".join(tree_result.stale_baseline))
        entries = baseline_mod.load(default_baseline_path())
        assert sum(entries.values()) == len(tree_result.grandfathered)

    def test_whole_tree_was_scanned(self, tree_result):
        assert tree_result.files_scanned > 200

    def test_runtime_budget(self, tree_result):
        assert tree_result.elapsed_s < LINT_BUDGET_S, (
            f"lint took {tree_result.elapsed_s:.1f}s, budget is "
            f"{LINT_BUDGET_S}s — the tier-1 gate must stay cheap")


# ---------------------------------------------------------------------------
# 2. Historical-bug regressions (the rules can't silently stop catching
#    the original bug classes)
# ---------------------------------------------------------------------------
def _expect_lines(fixture, rule):
    path = os.path.join(FIXTURES, fixture)
    with open(path) as f:
        lines = f.read().splitlines()
    expected = [i for i, line in enumerate(lines, 1)
                if f"expect-{rule}" in line]
    assert expected, f"fixture {fixture} has no expect-{rule} markers"
    return path, expected


@pytest.mark.parametrize("fixture,rule", [
    ("r1_memorystore_shape.py", "R1"),
    ("r4_leaked_task_shape.py", "R4"),
    ("r9_view_escape_shape.py", "R9"),
    ("r10_grow_only_shape.py", "R10"),
    ("r11_loop_stop_shape.py", "R11"),
    ("r12_lock_order_shape.py", "R12"),
    ("r13_affinity_shape.py", "R13"),
    ("r14_frame_drift_shape.py", "R14"),
])
def test_fixture_trips_exactly_on_marked_lines(fixture, rule):
    path, expected = _expect_lines(fixture, rule)
    res = run_lint([path], project_root=FIXTURES, rules=[rule],
                   baseline_path=None)
    assert not res.parse_errors
    got = sorted(v.line for v in res.violations)
    assert got == expected, (
        f"{rule} tripped on lines {got}, fixture marks {expected}:\n"
        + "\n".join(v.format() for v in res.violations))
    assert all(v.rule == rule for v in res.violations)


def test_r1_violation_explains_the_gc_chain():
    path, _ = _expect_lines("r1_memorystore_shape.py", "R1")
    res = run_lint([path], project_root=FIXTURES, rules=["R1"],
                   baseline_path=None)
    (v,) = res.violations
    # The message must carry the call path from the destructor to the
    # lock — that explanation is what makes the finding actionable.
    assert "__del__" in v.message
    assert "remove_local_ref" in v.message
    assert v.symbol == "MemoryStoreShape.delete"
    assert "self._lock" in v.message


def test_r4_flags_both_discard_shapes():
    path, _ = _expect_lines("r4_leaked_task_shape.py", "R4")
    res = run_lint([path], project_root=FIXTURES, rules=["R4"],
                   baseline_path=None)
    assert {v.symbol for v in res.violations} == {
        "ReadLoopOwnerShape.start", "spawn_and_forget"}


def test_r9_flags_all_three_escape_shapes():
    """Return, self-attribute, and closure-capture escapes each trip;
    the pinned twins and the local-use-only reader do not (ISSUE 9's
    view-lifetime contract)."""
    path, _ = _expect_lines("r9_view_escape_shape.py", "R9")
    res = run_lint([path], project_root=FIXTURES, rules=["R9"],
                   baseline_path=None)
    assert {v.symbol for v in res.violations} == {
        "UnpinnedEscapes.read", "UnpinnedEscapes.cache",
        "UnpinnedEscapes.serve_later.reply"}
    # every message names the contract's remedy
    assert all("pin" in v.message for v in res.violations)


def test_r12_cycle_explains_both_directions():
    """Each edge of the 2-lock SCC carries its call chain (including the
    callback hop) and names the reverse-order witness."""
    path, _ = _expect_lines("r12_lock_order_shape.py", "R12")
    res = run_lint([path], project_root=FIXTURES, rules=["R12"],
                   baseline_path=None)
    cyc = [v for v in res.violations if "lock-order cycle" in v.message]
    assert len(cyc) == 2
    assert any("on_evict" in v.message for v in cyc)  # the callback hop
    assert all("reverse" in v.message for v in cyc)
    (split,) = [v for v in res.violations if "GC context" in v.message]
    assert "RLock" in split.message and split.symbol == "CacheShape.insert"


def test_r13_violation_names_the_other_domain():
    path, _ = _expect_lines("r13_affinity_shape.py", "R13")
    res = run_lint([path], project_root=FIXTURES, rules=["R13"],
                   baseline_path=None)
    by_sym = {v.symbol: v.message for v in res.violations}
    # the loop-side site must point at the thread-side one and vice versa
    assert "'ProgressShape._drain'" in by_sym["ProgressShape.on_frame"]
    assert "'ProgressShape.on_frame'" in by_sym["ProgressShape._drain"]
    assert "['gc']" in by_sym["FinalizerShape.reset"]


def test_r14_flags_each_drift_class_once():
    """Send-only, read-never-sent, and type-incoherent each appear
    exactly once, against the intended method contract."""
    path, _ = _expect_lines("r14_frame_drift_shape.py", "R14")
    res = run_lint([path], project_root=FIXTURES, rules=["R14"],
                   baseline_path=None)
    msgs = sorted(v.message for v in res.violations)
    assert len(msgs) == 3
    assert sum("sent here but never read" in m for m in msgs) == 1
    assert sum("none of the" in m and "sends it" in m for m in msgs) == 1
    assert sum("type-incoherent" in m for m in msgs) == 1
    # opaque-handler and **-expanded contracts stay silent
    assert not any("ForwardBlob" in m or "ListNodes" in m for m in msgs)


# ---------------------------------------------------------------------------
# 3. Engine semantics
# ---------------------------------------------------------------------------
_LEAK = "import asyncio\n\ndef go(loop):\n    loop.create_task(work())\n"


def test_inline_disable_suppresses(tmp_path):
    f = tmp_path / "leak.py"
    f.write_text(_LEAK.replace(
        "loop.create_task(work())",
        "loop.create_task(work())  # raylint: disable=R4 -- test exemption"))
    res = run_lint([str(f)], project_root=str(tmp_path), baseline_path=None)
    assert not res.violations
    assert res.suppressed_count == 1


def test_disable_in_comment_block_above(tmp_path):
    f = tmp_path / "leak.py"
    f.write_text(_LEAK.replace(
        "    loop.create_task(work())",
        "    # raylint: disable=R4 -- justification on its own line,\n"
        "    # continued here\n"
        "    loop.create_task(work())"))
    res = run_lint([str(f)], project_root=str(tmp_path), baseline_path=None)
    assert not res.violations
    assert res.suppressed_count == 1


def test_disable_for_other_rule_does_not_suppress(tmp_path):
    f = tmp_path / "leak.py"
    f.write_text(_LEAK.replace(
        "loop.create_task(work())",
        "loop.create_task(work())  # raylint: disable=R6 -- wrong rule"))
    res = run_lint([str(f)], project_root=str(tmp_path), baseline_path=None)
    assert [v.rule for v in res.violations] == ["R4"]


def test_baseline_grandfathers_then_flags_growth(tmp_path):
    f = tmp_path / "leak.py"
    f.write_text(_LEAK)
    bl = tmp_path / "baseline.json"
    # Build the baseline from the current single violation...
    res = run_lint([str(f)], project_root=str(tmp_path), baseline_path=None)
    baseline_mod.save(str(bl), baseline_mod.counts(res.violations))
    res = run_lint([str(f)], project_root=str(tmp_path),
                   baseline_path=str(bl))
    assert not res.violations and len(res.grandfathered) == 1

    # ...then growth (a second leak in the same function) fails: the
    # baseline budget covers exactly the grandfathered occurrence count.
    f.write_text(_LEAK + "\ndef go2(loop):\n    loop.create_task(work())\n")
    res = run_lint([str(f)], project_root=str(tmp_path),
                   baseline_path=str(bl))
    assert len(res.violations) == 1 and len(res.grandfathered) == 1


def test_baseline_stale_entry_detected(tmp_path):
    f = tmp_path / "clean.py"
    f.write_text("x = 1\n")
    bl = tmp_path / "baseline.json"
    baseline_mod.save(str(bl), {"gone.py::R4::go::loop.create_task(w())": 1})
    res = run_lint([str(f)], project_root=str(tmp_path),
                   baseline_path=str(bl))
    assert not res.violations
    assert res.stale_baseline == ["gone.py::R4::go::loop.create_task(w())"]


def test_baseline_key_survives_line_shifts(tmp_path):
    f = tmp_path / "leak.py"
    f.write_text(_LEAK)
    res1 = run_lint([str(f)], project_root=str(tmp_path), baseline_path=None)
    f.write_text("# a new comment pushing everything down\n\n" + _LEAK)
    res2 = run_lint([str(f)], project_root=str(tmp_path), baseline_path=None)
    assert res1.violations[0].line != res2.violations[0].line
    assert res1.violations[0].key() == res2.violations[0].key()


def test_cli_json_output_and_exit_codes(tmp_path, capsys):
    f = tmp_path / "leak.py"
    f.write_text(_LEAK)
    rc = lint_main([str(f), "--project-root", str(tmp_path),
                    "--no-baseline", "--format", "json"])
    out = json.loads(capsys.readouterr().out)
    assert rc == 1
    assert out["ok"] is False
    (v,) = out["violations"]
    assert v["rule"] == "R4" and v["path"] == "leak.py" and v["key"]

    clean = tmp_path / "clean.py"
    clean.write_text("x = 1\n")
    rc = lint_main([str(clean), "--project-root", str(tmp_path),
                    "--no-baseline", "--format", "json"])
    assert rc == 0
    assert json.loads(capsys.readouterr().out)["ok"] is True


def test_cli_lists_all_rules(capsys):
    assert lint_main(["--list-rules"]) == 0
    out = capsys.readouterr().out
    for n in range(1, 15):
        assert f"R{n}:" in out


def test_cli_sarif_output(tmp_path, capsys):
    f = tmp_path / "leak.py"
    f.write_text(_LEAK)
    rc = lint_main([str(f), "--project-root", str(tmp_path),
                    "--no-baseline", "--format", "sarif"])
    sarif = json.loads(capsys.readouterr().out)
    assert rc == 1
    assert sarif["version"] == "2.1.0"
    (run,) = sarif["runs"]
    assert {r["id"] for r in run["tool"]["driver"]["rules"]} >= {
        "R1", "R12", "R13", "R14"}
    (result,) = run["results"]
    assert result["ruleId"] == "R4" and result["level"] == "error"
    loc = result["locations"][0]["physicalLocation"]
    assert loc["artifactLocation"]["uri"] == "leak.py"
    assert loc["region"]["startLine"] == 4
    # the fingerprint is the line-free baseline key: stable across edits
    assert result["partialFingerprints"]["raylintKey/v1"].startswith(
        "leak.py::R4::")


def test_cli_changed_scopes_the_report(tmp_path, capsys):
    """--changed lints everything (cross-module rules keep precision)
    but only *reports* violations in files changed vs git HEAD."""
    import subprocess

    def git(*args):
        subprocess.run(["git", "-c", "user.email=t@t", "-c",
                        "user.name=t", *args], cwd=str(tmp_path),
                       check=True, capture_output=True)

    git("init", "-q")
    committed = tmp_path / "committed_leak.py"
    committed.write_text(_LEAK)
    git("add", "committed_leak.py")
    git("commit", "-qm", "seed")
    fresh = tmp_path / "fresh_leak.py"
    fresh.write_text(_LEAK)

    rc = lint_main([str(tmp_path), "--project-root", str(tmp_path),
                    "--no-baseline", "--changed", "--format", "json"])
    out = json.loads(capsys.readouterr().out)
    assert rc == 1
    assert [v["path"] for v in out["violations"]] == ["fresh_leak.py"]

    # without --changed the committed file's violation reports too
    rc = lint_main([str(tmp_path), "--project-root", str(tmp_path),
                    "--no-baseline", "--format", "json"])
    out = json.loads(capsys.readouterr().out)
    assert {v["path"] for v in out["violations"]} == {
        "committed_leak.py", "fresh_leak.py"}


# ---------------------------------------------------------------------------
# 4. R5's dynamic half: auto-generated pickle round-trip over every
#    public exception class
# ---------------------------------------------------------------------------
def _public_exception_classes():
    out = []
    for name in dir(exc_mod):
        if name.startswith("_"):
            continue
        obj = getattr(exc_mod, name)
        if (inspect.isclass(obj) and issubclass(obj, BaseException)
                and obj.__module__ == exc_mod.__name__):
            out.append(obj)
    assert len(out) >= 15  # the hierarchy, not a subset
    return sorted(out, key=lambda c: c.__name__)


# Representative values by field name; everything else is synthesized
# from the parameter's default type. ``cause`` is excluded here (it is
# exercised with a real exception in test_task_error_cause_survives).
_SAMPLES = {
    "timeline": [(1.5, "detected"), (2.5, "fenced")],
    "queue_depths": {"replica-a": 3, "replica-b": 0},
    "incarnation": 7,
    "cause": None,
    # TrainingWorkerError: which ranks died
    "failed_ranks": [0, 3],
    # ObjectReconstructionFailedError: the attempted lineage chain
    "chain": [{"object_id": "aa" * 18, "task": "f", "why": "replayed"}],
}


def _synthesize_kwargs(cls):
    kwargs = {}
    params = list(inspect.signature(cls.__init__).parameters.values())[1:]
    for p in params:
        if p.kind in (p.VAR_POSITIONAL, p.VAR_KEYWORD):
            continue
        if p.name in _SAMPLES:
            val = _SAMPLES[p.name]
        elif isinstance(p.default, bool):
            val = p.default
        elif isinstance(p.default, int):
            val = 3
        elif isinstance(p.default, float):
            val = 2.5
        else:  # str defaults and required params: a distinctive string
            val = f"v-{p.name}"
        if val is not None:
            kwargs[p.name] = val
    return kwargs


def _fields(e):
    out = {"__type__": type(e).__name__, "__str__": str(e), "args": e.args}
    for k, v in vars(e).items():
        out[k] = v.to_dict() if isinstance(v, exc_mod.DeathContext) else v
    return out


@pytest.mark.parametrize("cls", _public_exception_classes(),
                         ids=lambda c: c.__name__)
def test_exception_pickle_round_trip(cls):
    kwargs = _synthesize_kwargs(cls)
    exc = cls(**kwargs) if kwargs else cls("v-message")
    clone = pickle.loads(pickle.dumps(exc))
    assert type(clone) is cls
    assert _fields(clone) == _fields(exc)


def test_task_error_cause_survives():
    try:
        raise ValueError("boom")
    except ValueError as e:
        exc = exc_mod.RayTaskError.from_exception(e, "f")
    clone = pickle.loads(pickle.dumps(exc))
    assert isinstance(clone.cause, ValueError)
    assert clone.cause.args == ("boom",)
    assert clone.function_name == "f"
    assert "boom" in clone.traceback_str


def test_task_error_unpicklable_cause_dropped_not_fatal():
    exc = exc_mod.RayTaskError("f", "tb", cause=ValueError("ok"))
    exc.cause = ValueError(lambda: None)  # unpicklable payload
    clone = pickle.loads(pickle.dumps(exc))
    assert clone.cause is None
    assert clone.traceback_str == "tb"


def test_death_context_round_trip():
    ctx = exc_mod.DeathContext("node-abc", 4, "partition fenced",
                               [(1.0, "missed hb"), (2.0, "fenced")])
    clone = pickle.loads(pickle.dumps(ctx))
    assert clone.to_dict() == ctx.to_dict()


# ---------------------------------------------------------------------------
# conftest hardening (ISSUE 7 satellite): the FAST tier runs with asyncio
# debug mode on and never-awaited coroutines promoted to errors. These
# meta-tests pin the contract so a conftest refactor can't drop it.
# ---------------------------------------------------------------------------
def test_asyncio_debug_mode_enabled():
    assert os.environ.get("PYTHONASYNCIODEBUG") == "1"
    loop = asyncio.new_event_loop()
    try:
        assert loop.get_debug()
    finally:
        loop.close()


def test_never_awaited_warning_is_an_error():
    with pytest.raises(RuntimeWarning, match="was never awaited"):
        warnings.warn("coroutine 'leaky' was never awaited", RuntimeWarning)
