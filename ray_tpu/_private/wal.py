"""Write-ahead log for the head control plane (GCS durability).

The debounced-snapshot persistence this replaces (``head_save_debounce_s``)
silently lost every mutation inside the debounce window on a head
``kill -9``. Here every authoritative GCS mutation appends one record to
an append-only log and the mutating RPC replies only after the record is
durable, so acknowledged state survives any head death (reference: Ray's
Redis-backed GCS fault tolerance, ``src/ray/gcs/gcs_server/`` — the log
plays the role of the external store's operation stream).

On-disk format (little-endian)::

    magic:  b"RTPUWAL1"                      (8 bytes, once per file)
    record: u32 length | u32 crc32(payload) | payload
    payload = pickle((seq, op, data))

Durability model:

* **Group commit** — appends buffer in memory; a flusher task writes and
  ``fsync``\\ s the batch at most ``gcs_wal_fsync_interval_ms`` later and
  resolves every batched append's future at once. One fsync amortizes
  across an entire mutation burst (a 1,000-actor creation storm pays
  ~interval, not 1,000 fsyncs).
* **Torn-tail tolerance** — recovery replays records until the first
  short/oversized/bad-CRC record, truncates the file there, and carries
  on. A head killed mid-write (or mid-``fsync``) never crash-loops on its
  own log.
* **Snapshot-and-truncate compaction** — when the log outgrows
  ``gcs_wal_compact_bytes`` the head saves a full snapshot stamped with
  the latest sequence number, then ``rotate()``\\ s the log. Replay skips
  records with ``seq <= snapshot_seq``, so a crash *between* snapshot
  save and rotate is harmless (the stale prefix is simply ignored).
"""

from __future__ import annotations

import asyncio
import os
import pickle
import struct
import time
import zlib
from typing import Any, Dict, List, Optional, Tuple

MAGIC = b"RTPUWAL1"
_HDR = struct.Struct("<II")  # length, crc32(payload)
# A length prefix beyond this is garbage from a torn write, not a real
# record (the largest legitimate record is one KV value plus envelope).
MAX_RECORD_BYTES = 256 * 1024 * 1024


def _encode(seq: int, op: str, data: Any) -> bytes:
    payload = pickle.dumps((seq, op, data), protocol=pickle.HIGHEST_PROTOCOL)
    return _HDR.pack(len(payload), zlib.crc32(payload) & 0xFFFFFFFF) + payload


def _fsync_dir(path: str) -> None:
    """fsync the parent directory so a freshly created/replaced log file
    survives a machine crash, not just a process kill."""
    try:
        fd = os.open(os.path.dirname(os.path.abspath(path)) or ".",
                     os.O_RDONLY)
        try:
            os.fsync(fd)
        finally:
            os.close(fd)
    except OSError:
        pass  # e.g. directories that don't support fsync


def scan(path: str, repair: bool = True
         ) -> Tuple[List[Tuple[int, str, Any]], int]:
    """Read every intact record; return ``(records, valid_end_offset)``.

    Stops at the first torn or corrupt record (short header, impossible
    length, CRC mismatch, unpicklable payload) — everything after a bad
    record is untrusted, because record boundaries can no longer be
    located. With ``repair`` the file is truncated to the last valid
    offset so subsequent appends extend a clean log.
    """
    if not os.path.exists(path):
        return [], 0
    with open(path, "rb") as f:
        data = f.read()
    off = 0
    if data[:len(MAGIC)] == MAGIC:
        off = len(MAGIC)
    elif data:
        # unrecognized preamble: nothing in this file can be trusted
        if repair:
            with open(path, "wb") as f:
                f.write(MAGIC)
                f.flush()
                os.fsync(f.fileno())
        return [], len(MAGIC)
    records: List[Tuple[int, str, Any]] = []
    valid_end = off
    while True:
        if off + _HDR.size > len(data):
            break  # torn header
        length, crc = _HDR.unpack_from(data, off)
        if length > MAX_RECORD_BYTES or off + _HDR.size + length > len(data):
            break  # impossible/torn body
        payload = data[off + _HDR.size:off + _HDR.size + length]
        if zlib.crc32(payload) & 0xFFFFFFFF != crc:
            break  # corrupt record: stop, trust nothing past it
        try:
            rec = pickle.loads(payload)
        except Exception:
            break
        if not (isinstance(rec, tuple) and len(rec) == 3):
            break
        records.append(rec)
        off += _HDR.size + length
        valid_end = off
    if repair and valid_end < len(data):
        with open(path, "r+b") as f:
            f.truncate(valid_end)
            f.flush()
            os.fsync(f.fileno())
    return records, valid_end


def replay(path: str, snapshot_seq: int = 0, repair: bool = True
           ) -> List[Tuple[int, str, Any]]:
    """Records to apply on top of a snapshot stamped ``snapshot_seq``."""
    records, _ = scan(path, repair=repair)
    return [r for r in records if r[0] > snapshot_seq]


class WriteAheadLog:
    """Append-only, CRC-checksummed, group-committed operation log.

    Construct (sync — opens/repairs the file), then ``start()`` on the
    serving event loop. ``append()`` resolves once the record is fsynced.
    """

    def __init__(self, path: str, fsync_interval_ms: float = 2.0):
        self.path = path
        self.fsync_interval_s = max(0.0, float(fsync_interval_ms)) / 1000.0
        existing, valid_end = scan(path, repair=True)
        #: last sequence number present in the log (callers bump past the
        #: snapshot's seq via ``reset_seq`` after recovery merges both)
        self.seq = existing[-1][0] if existing else 0
        # the open-time scan already read and CRC-checked every record;
        # hand it to recovery via take_boot_records() instead of making
        # _load_state re-read the whole file
        self._boot_records: List[Tuple[int, str, Any]] = existing
        fresh = not os.path.exists(path) or valid_end == 0
        self._f = open(path, "ab")
        if fresh and self._f.tell() == 0:
            self._f.write(MAGIC)
            self._f.flush()
            os.fsync(self._f.fileno())
            _fsync_dir(path)
        self.size_bytes = self._f.tell()
        self.records_appended = 0
        self.fsyncs = 0
        self.last_fsync_at = time.monotonic()
        self._pending: List[Tuple[bytes, "asyncio.Future"]] = []
        self._wake: Optional[asyncio.Event] = None
        self._io_lock: Optional[asyncio.Lock] = None
        self._flusher: Optional["asyncio.Task"] = None
        self._closed = False

    # ------------------------------------------------------------ lifecycle
    def start(self) -> None:
        """Arm the group-commit flusher (running-loop context)."""
        from ray_tpu._private.async_util import spawn_tracked

        if self._flusher is not None:
            return
        self._wake = asyncio.Event()
        self._io_lock = asyncio.Lock()
        self._flusher = spawn_tracked(self._flush_loop(), "wal-flusher")

    def reset_seq(self, seq: int) -> None:
        self.seq = max(self.seq, int(seq))

    def take_boot_records(self) -> List[Tuple[int, str, Any]]:
        """The records found (and repaired past) when the log was opened
        — the boot-time replay source. Cleared on first call so a large
        log's decoded records aren't pinned for the process lifetime."""
        recs, self._boot_records = self._boot_records, []
        return recs

    async def close(self) -> None:
        self._closed = True
        if self._flusher is not None:
            if self._wake is not None:
                self._wake.set()
            try:
                await self._flusher
            except Exception:
                pass
            self._flusher = None
        self._drain_pending_sync()
        try:
            self._f.close()
        except OSError:
            pass

    def close_sync(self) -> None:
        """Shutdown-path close: flush whatever is buffered, no loop."""
        self._closed = True
        self._drain_pending_sync()
        try:
            self._f.close()
        except OSError:
            pass

    def _drain_pending_sync(self) -> None:
        pending, self._pending = self._pending, []
        if not pending:
            return
        err = None
        try:
            self._write_and_sync(b"".join(body for body, _ in pending))
        except OSError as e:
            err = e
        for _, fut in pending:
            try:
                if fut.done():
                    continue
                if err is None:
                    fut.set_result(None)
                else:  # never falsely ack a write that failed
                    fut.set_exception(
                        RuntimeError(f"WAL write failed: {err!r}"))
            except Exception:
                pass  # future's loop may already be closed at shutdown

    # -------------------------------------------------------------- appends
    def append_nowait(self, op: str, data: Any
                      ) -> Tuple[int, "asyncio.Future"]:
        """Buffer one record; the future resolves when it is durable."""
        if self._closed:
            raise RuntimeError("WAL is closed")
        self.seq += 1
        seq = self.seq
        fut = asyncio.get_running_loop().create_future()
        self._pending.append((_encode(seq, op, data), fut))
        self.records_appended += 1
        if self._wake is not None:
            self._wake.set()
        return seq, fut

    async def append(self, op: str, data: Any) -> int:
        """Append and wait until the record is fsynced (group commit)."""
        seq, fut = self.append_nowait(op, data)
        await fut
        return seq

    async def flush(self) -> None:
        """Force everything buffered to disk now (bypasses the window)."""
        if not self._pending:
            return
        await self._commit_batch()

    # ----------------------------------------------------------- compaction
    async def rotate(self, snapshot_seq: int) -> None:
        """Truncate after a durably saved snapshot stamped
        ``snapshot_seq``: replace the log with a fresh file keeping only
        records *newer* than the snapshot — both those flushed to the old
        file while the snapshot was being written and everything still
        pending. Records at or below the snapshot's seq are covered by
        the snapshot and dropped.
        """
        async with self._io_lock:
            pending, self._pending = self._pending, []
            tmp = f"{self.path}.rotate.tmp"
            old = self._f
            old.flush()  # make the old tail scannable below

            def _swap() -> int:
                keep, _ = scan(self.path, repair=False)
                with open(tmp, "wb") as nf:
                    nf.write(MAGIC)
                    for rec in keep:
                        if rec[0] > snapshot_seq:
                            nf.write(_encode(*rec))
                    for body, _ in pending:
                        nf.write(body)
                    nf.flush()
                    os.fsync(nf.fileno())
                os.replace(tmp, self.path)
                _fsync_dir(self.path)
                return os.path.getsize(self.path)

            try:
                size = await asyncio.to_thread(_swap)
            except Exception:
                # rotation failed before the replace took effect: hand the
                # stolen appends back to the flusher (old file is intact)
                # instead of leaving their futures unresolved forever
                self._pending = pending + self._pending
                if self._wake is not None:
                    self._wake.set()
                raise
            self._f = open(self.path, "ab")
            try:
                old.close()
            except OSError:
                pass
            self.size_bytes = size
            self.fsyncs += 1
            self.last_fsync_at = time.monotonic()
            for _, fut in pending:
                if not fut.done():
                    fut.set_result(None)

    # ------------------------------------------------------------- internals
    async def _flush_loop(self) -> None:
        while not self._closed:
            await self._wake.wait()
            self._wake.clear()
            if self._closed:
                break
            if self.fsync_interval_s > 0:
                # group-commit window: let a mutation burst pile on so the
                # whole batch shares one write+fsync
                await asyncio.sleep(self.fsync_interval_s)
            await self._commit_batch()

    async def _commit_batch(self) -> None:
        async with self._io_lock:
            pending, self._pending = self._pending, []
            if not pending:
                return
            buf = b"".join(body for body, _ in pending)
            try:
                await asyncio.to_thread(self._write_and_sync, buf)
            except Exception as e:  # disk full / EIO: fail the acks, keep
                # roll the file back to the last offset known durable: a
                # torn record left mid-file would make recovery's scan
                # stop THERE and silently discard every LATER acked batch
                # ("kill -9 loses nothing acked" would quietly break)
                try:
                    await asyncio.to_thread(self._rollback_to_last_sync)
                except Exception:
                    # can't restore a clean tail: poison the log so no
                    # future append can be falsely acked past the garbage
                    self._closed = True
                for _, fut in pending:  # serving reads — callers see the
                    if not fut.done():  # error instead of a false ack
                        fut.set_exception(
                            RuntimeError(f"WAL write failed: {e!r}"))
                return
            self.fsyncs += 1
            self.last_fsync_at = time.monotonic()
            for _, fut in pending:
                if not fut.done():
                    fut.set_result(None)

    def _write_and_sync(self, buf: bytes) -> None:
        self._f.write(buf)
        self._f.flush()
        os.fsync(self._f.fileno())
        # only advanced after a SUCCESSFUL fsync: on a failed write this
        # is the rollback point (_rollback_to_last_sync)
        self.size_bytes = self._f.tell()

    def _rollback_to_last_sync(self) -> None:
        """Drop a torn record a failed write may have left: reopen the
        file truncated at the last fsynced offset so later appends extend
        a clean log (O_APPEND ignores seeks — reopen, don't rewind)."""
        try:
            self._f.close()  # discards any half-buffered garbage
        except OSError:
            pass
        with open(self.path, "r+b") as f:
            f.truncate(self.size_bytes)
            f.flush()
            os.fsync(f.fileno())
        self._f = open(self.path, "ab")

    # ---------------------------------------------------------------- stats
    def stats(self) -> Dict[str, Any]:
        return {
            "path": self.path,
            "size_bytes": self.size_bytes,
            "seq": self.seq,
            "records_appended": self.records_appended,
            "fsyncs": self.fsyncs,
            "last_fsync_age_s": round(
                time.monotonic() - self.last_fsync_at, 3),
            "pending": len(self._pending),
        }
