"""AlgorithmConfig — fluent builder (reference:
rllib/algorithms/algorithm_config.py, 3.5k LoC; ``framework`` :1205. Here
JAX is the only framework, so ``framework("jax")`` is the default and the
torch/tf paths don't exist).
"""

from __future__ import annotations

import copy
from typing import Any, Callable, Dict, Optional, Tuple, Type, Union


class AlgorithmConfig:
    def __init__(self, algo_class: Optional[type] = None):
        self.algo_class = algo_class
        # environment
        self.env: Optional[Union[str, Callable]] = None
        self.env_config: Dict = {}
        # env runners
        self.num_env_runners = 2
        self.num_envs_per_env_runner = 4
        self.rollout_fragment_length = 64
        self.explore = True
        # training
        self.gamma = 0.99
        self.lr = 3e-4
        self.train_batch_size = 2048
        self.minibatch_size = 128
        self.num_epochs = 4
        self.grad_clip = 0.5
        self.seed = 0
        # learners
        self.num_learners = 0
        self.resources_per_learner: Optional[Dict] = None
        # model
        self.model: Dict = {"hiddens": (64, 64), "activation": "tanh"}
        # framework (always jax; kept for API parity)
        self.framework_str = "jax"
        # fault tolerance (reference: restart_failed_env_runners)
        self.restart_failed_env_runners = True
        # obs/action connector pipeline (reference: rllib/connectors/)
        self.connector = None

    # ------------------------------------------------------- fluent setters
    def environment(self, env=None, *, env_config: Optional[Dict] = None
                    ) -> "AlgorithmConfig":
        if env is not None:
            self.env = env
        if env_config is not None:
            self.env_config = env_config
        return self

    def env_runners(self, *, num_env_runners: Optional[int] = None,
                    num_envs_per_env_runner: Optional[int] = None,
                    rollout_fragment_length: Optional[int] = None,
                    explore: Optional[bool] = None,
                    connector=None) -> "AlgorithmConfig":
        if num_env_runners is not None:
            self.num_env_runners = num_env_runners
        if num_envs_per_env_runner is not None:
            self.num_envs_per_env_runner = num_envs_per_env_runner
        if rollout_fragment_length is not None:
            self.rollout_fragment_length = rollout_fragment_length
        if explore is not None:
            self.explore = explore
        if connector is not None:
            self.connector = connector
        return self

    def training(self, **kwargs) -> "AlgorithmConfig":
        for k, v in kwargs.items():
            if not hasattr(self, k) and k not in self._training_keys():
                raise ValueError(f"unknown training key {k!r}")
            setattr(self, k, v)
        return self

    def _training_keys(self):
        return set()

    def learners(self, *, num_learners: Optional[int] = None,
                 resources_per_learner: Optional[Dict] = None
                 ) -> "AlgorithmConfig":
        if num_learners is not None:
            self.num_learners = num_learners
        if resources_per_learner is not None:
            self.resources_per_learner = resources_per_learner
        return self

    def framework(self, framework: str = "jax") -> "AlgorithmConfig":
        if framework != "jax":
            raise ValueError(
                "this build is TPU/JAX-native; framework must be 'jax'")
        self.framework_str = framework
        return self

    def fault_tolerance(self, *, restart_failed_env_runners: Optional[bool]
                        = None) -> "AlgorithmConfig":
        if restart_failed_env_runners is not None:
            self.restart_failed_env_runners = restart_failed_env_runners
        return self

    def rl_module(self, *, model: Optional[Dict] = None) -> "AlgorithmConfig":
        if model:
            self.model.update(model)
        return self

    def debugging(self, *, seed: Optional[int] = None) -> "AlgorithmConfig":
        if seed is not None:
            self.seed = seed
        return self

    # --------------------------------------------------------------- build
    def copy(self) -> "AlgorithmConfig":
        return copy.deepcopy(self)

    def to_dict(self) -> Dict:
        return {k: v for k, v in self.__dict__.items()
                if k != "algo_class"}

    def build(self, use_tune_dirs: bool = False):
        if self.algo_class is None:
            raise ValueError("config has no algo_class; use PPOConfig() etc.")
        return self.algo_class(config=self)

    # ------------------------------------------------------------ env utils
    def make_env(self) -> Callable:
        env = self.env
        env_config = self.env_config
        if callable(env):
            return lambda: env(env_config)
        if isinstance(env, str):
            def creator():
                import gymnasium as gym

                return gym.make(env, **env_config)

            return creator
        raise ValueError(f"unsupported env spec {env!r}")

    def module_spec(self):
        from ray_tpu.rllib.core.rl_module import RLModuleSpec

        probe = self.make_env()()
        try:
            import gymnasium as gym

            obs_space = probe.observation_space
            act_space = probe.action_space
            # catalog routing (reference: models/catalog.py get_model_v2):
            # rank-3 obs -> ConvModule; model={'use_lstm': True} -> LSTM
            obs_shape = (tuple(obs_space.shape)
                         if len(obs_space.shape) == 3 else None)
            obs_dim = (int(obs_space.shape[0]) if obs_shape is None else 0)
            if self.connector is not None and obs_shape is None:
                # FrameStack-style connectors widen the feature dim
                # (pipelines expose obs_multiplier; bare connectors
                # obs_dim_multiplier)
                obs_dim *= getattr(
                    self.connector, "obs_multiplier",
                    getattr(self.connector, "obs_dim_multiplier", 1))
            common = dict(
                hiddens=tuple(self.model.get("hiddens", (64, 64))),
                activation=self.model.get("activation", "tanh"),
                obs_shape=obs_shape,
                conv_filters=self.model.get("conv_filters"),
                use_lstm=bool(self.model.get("use_lstm", False)),
                lstm_cell_size=int(self.model.get("lstm_cell_size", 64)))
            if isinstance(act_space, gym.spaces.Discrete):
                return RLModuleSpec(
                    obs_dim=obs_dim, action_dim=int(act_space.n),
                    discrete=True, **common)
            return RLModuleSpec(
                obs_dim=obs_dim, action_dim=int(act_space.shape[0]),
                discrete=False, **common)
        finally:
            probe.close()

    def learner_config_dict(self) -> Dict:
        return {
            "lr": self.lr, "grad_clip": self.grad_clip,
            "num_epochs": self.num_epochs,
            "minibatch_size": self.minibatch_size, "seed": self.seed,
            "gamma": self.gamma,  # TD/V-trace targets must match rollouts
        }
