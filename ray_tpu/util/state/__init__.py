"""State API (reference: python/ray/util/state/api.py — list_actors :782,
list_tasks :1014, summaries :1376; aggregated by
dashboard/state_aggregator.py StateAPIManager :141).

Queries go to the head's info handlers; per-worker live state rides the
task-event store the way the reference pairs GCS data with
``QueryAllWorkerStates``.
"""

from __future__ import annotations

import collections
from typing import Any, Dict, List, Optional

__all__ = [
    "list_actors", "list_nodes", "list_tasks", "list_placement_groups",
    "list_jobs", "list_workers", "list_objects", "object_summary",
    "summarize_tasks", "summarize_actors", "summarize_objects",
    "get_node_stats", "profile_worker", "capture_jax_trace",
    "list_cluster_events",
]


def _worker():
    from ray_tpu._private import worker as worker_mod

    w = worker_mod.global_worker
    if w is None or not w.connected:
        raise RuntimeError("ray_tpu.init() must be called first")
    return w


def _call(method: str, payload: Optional[Dict] = None):
    w = _worker()
    return w._acall(w.head.call(method, payload or {}))


def _apply_filters(rows: List[Dict], filters) -> List[Dict]:
    """filters: [(key, op, value)] with op in ('=', '!=', '<', '<=',
    '>', '>=', 'contains', '!contains') — the reference's predicate set
    (reference: python/ray/util/state/api.py StateApiClient filters +
    common.py supported_filters). Ordering ops compare numerically when
    both sides parse as floats, else lexically."""

    def _cmp(a, b) -> Optional[int]:
        try:
            fa, fb = float(a), float(b)
            return (fa > fb) - (fa < fb)
        except (TypeError, ValueError):
            sa, sb = str(a), str(b)
            return (sa > sb) - (sa < sb)

    for key, op, value in filters or []:
        if op == "=":
            rows = [r for r in rows if str(r.get(key)) == str(value)]
        elif op == "!=":
            rows = [r for r in rows if str(r.get(key)) != str(value)]
        elif op in ("<", "<=", ">", ">="):
            want = {"<": (-1,), "<=": (-1, 0), ">": (1,), ">=": (0, 1)}[op]
            rows = [r for r in rows
                    if r.get(key) is not None
                    and _cmp(r.get(key), value) in want]
        elif op == "contains":
            rows = [r for r in rows if r.get(key) is not None
                    and str(value) in str(r.get(key))]
        elif op == "!contains":
            rows = [r for r in rows if r.get(key) is not None
                    and str(value) not in str(r.get(key))]
        else:
            raise ValueError(f"unsupported filter op {op!r}")
    return rows


def list_actors(filters=None, limit: int = 1000) -> List[Dict]:
    rows = _call("ListActors")
    return _apply_filters(rows, filters)[:limit]


def list_nodes(filters=None, limit: int = 1000) -> List[Dict]:
    from ray_tpu._private.resources import ResourceSet

    rows = _call("ListNodes")
    for r in rows:
        r["state"] = "ALIVE" if r.get("alive") else "DEAD"
        for key in ("resources_total", "resources_available"):
            if isinstance(r.get(key), dict):
                r[key] = ResourceSet.from_wire(r[key]).to_dict()
    return _apply_filters(rows, filters)[:limit]


def list_tasks(filters=None, limit: int = 10000) -> List[Dict]:
    w = _worker()
    w.flush_task_events()
    payload: Dict = {"limit": limit * 4}
    # an equality filter on job_id prefilters server-side — the head
    # scans its 100k-entry ring once instead of shipping 4x limit rows
    # for the client to discard
    for key, op, value in filters or []:
        if key == "job_id" and op == "=":
            payload["job_id"] = value
            break
    rows = _call("ListTaskEvents", payload)
    return _apply_filters(rows, filters)[:limit]


def list_placement_groups(filters=None, limit: int = 1000) -> List[Dict]:
    rows = _call("ListPlacementGroups")
    return _apply_filters(rows, filters)[:limit]


def list_jobs(filters=None, limit: int = 1000) -> List[Dict]:
    rows = _call("ListJobs")
    return _apply_filters(rows, filters)[:limit]


def _call_agent(addr: Dict, method: str, payload: Optional[Dict] = None):
    """Live per-node query straight to a node agent (reference: the state
    API pairs GCS tables with NodeManager::QueryAllWorkerStates)."""
    w = _worker()

    async def go():
        client = await w._owner_client(addr)
        return await client.call(method, payload or {}, timeout=10)

    return w._acall(go())


def _each_alive_agent():
    for node in _call("ListNodes"):
        if node.get("alive") and node.get("addr"):
            yield node


def list_workers(filters=None, limit: int = 1000) -> List[Dict]:
    """All worker processes across the cluster (reference:
    util/state/api.py list_workers)."""
    rows: List[Dict] = []
    for node in _each_alive_agent():
        try:
            rows.extend(_call_agent(node["addr"], "ListWorkers"))
        except Exception:
            continue  # node died mid-listing
        if len(rows) >= limit:
            break
    return _apply_filters(rows, filters)[:limit]


def list_objects(filters=None, limit: int = 1000,
                 detail: bool = True) -> List[Dict]:
    """Every owned object across the cluster with creation provenance —
    callsite, creator task/actor, size, refs, residency tier (ISSUE 15;
    reference: util/state/api.py list_objects over core-worker object
    views). ``detail=False`` falls back to the raw per-node store
    listing (no owner join — objects whose owner died still show)."""
    if detail:
        out = _call("ObjectSummary", {"detail": True, "limit": limit})
        return _apply_filters(out.get("rows") or [], filters)[:limit]
    rows: List[Dict] = []
    for node in _each_alive_agent():
        try:
            rows.extend(_call_agent(node["addr"], "ListStoreObjects",
                                    {"limit": limit}))
        except Exception:
            continue
        if len(rows) >= limit:
            break
    return _apply_filters(rows, filters)[:limit]


def list_cluster_events(severity: Optional[str] = None,
                        label: Optional[str] = None,
                        limit: int = 1000) -> List[Dict]:
    """Structured cluster events — node deaths, actor failures, OOM kills,
    autoscaler actions (reference: src/ray/util/event.h RAY_EVENT files
    surfaced by the dashboard event module)."""
    import os

    import ray_tpu
    from ray_tpu._private.event import read_events

    node = ray_tpu._global_node
    session_dir = (node.session_dir if node is not None
                   else os.environ.get("RAY_TPU_SESSION_DIR"))
    out: List[Dict] = []
    if session_dir:
        out.extend(read_events(session_dir, severity=severity,
                               label=label, limit=limit))
    # aggregate remote nodes' events (their session dirs live on their
    # machines); de-dup against the local read for shared-dir test setups
    seen = {(e.get("component"), e.get("pid"), e.get("timestamp"))
            for e in out}
    for n in _each_alive_agent():
        try:
            remote = _call_agent(n["addr"], "ListEvents",
                                 {"severity": severity, "label": label,
                                  "limit": limit})
        except Exception:
            continue
        for e in remote:
            key = (e.get("component"), e.get("pid"), e.get("timestamp"))
            if key not in seen:
                seen.add(key)
                out.append(e)
    out.sort(key=lambda e: e.get("timestamp", 0.0))
    return out[-limit:]


def get_node_stats() -> List[Dict]:
    """Per-node reporter samples: cpu/mem/disk/workers/object-store/TPU
    (reference: dashboard reporter_agent.py:277 stats surface)."""
    rows = []
    for node in _each_alive_agent():
        try:
            stats = _call_agent(node["addr"], "GetNodeStats")
        except Exception:
            continue
        if stats:
            rows.append(stats)
    return rows


def _worker_direct_addr(worker_id: str) -> Dict:
    for w in list_workers(limit=100000):
        if w["worker_id"] == worker_id and w.get("direct_addr") \
                and w.get("alive"):
            return w["direct_addr"]
    raise ValueError(f"no live worker {worker_id!r} with a direct address")


def profile_worker(worker_id: str, duration_s: float = 2.0) -> Dict:
    """Sample a worker's Python stacks (py-spy analog; reference:
    dashboard/modules/reporter/profile_manager.py:61-97). Returns
    {"pid", "duration_s", "folded": {stack: count}} — folded-stacks text
    for flamegraph.pl / speedscope."""
    addr = _worker_direct_addr(worker_id)
    w = _worker()

    async def go():
        client = await w._owner_client(addr)
        return await client.call("SampleStacks",
                                 {"duration_s": duration_s},
                                 timeout=duration_s + 30)

    return w._acall(go(), timeout=duration_s + 35)


def capture_jax_trace(worker_id: str, duration_s: float = 2.0,
                      out_dir: Optional[str] = None) -> Dict:
    """Capture a jax.profiler device trace inside a worker (SURVEY §5 —
    device-trace profiling surfaced through the same reporter API).
    Returns {"trace_dir", "files"} loadable in TensorBoard/Perfetto."""
    addr = _worker_direct_addr(worker_id)
    w = _worker()

    async def go():
        client = await w._owner_client(addr)
        # generous window: jax.profiler start/stop on a remote-tunnel TPU
        # can take tens of seconds beyond the capture itself
        return await client.call(
            "CaptureJaxTrace",
            {"duration_s": duration_s, "out_dir": out_dir},
            timeout=duration_s + 180)

    return w._acall(go(), timeout=duration_s + 185)


def summarize_objects(group_by: str = "node") -> Dict[str, Any]:
    """Cluster object totals grouped by ``node`` / ``callsite`` /
    ``creator`` / ``tier`` (reference: ``ray summary objects`` +
    ``ray memory`` group-by; the head's ObjectSummary does the
    fan-out + merge)."""
    if group_by not in ("node", "callsite", "creator", "tier"):
        raise ValueError(
            f"group_by must be node|callsite|creator|tier, got {group_by!r}")
    out = _call("ObjectSummary", {"group_by": group_by, "limit": 100000})
    return out.get("groups") or {}


def object_summary(group_by: str = "node", detail: bool = False,
                   limit: int = 10000) -> Dict[str, Any]:
    """Full ObjectSummary reply: per-node store/tier stats, leak
    suspects, groups, and (with detail) per-object provenance rows —
    what ``ray_tpu memory`` renders."""
    return _call("ObjectSummary", {"group_by": group_by, "detail": detail,
                                   "limit": limit})


def summarize_tasks() -> Dict[str, Dict]:
    """Per-function-name counts by state (reference: ``ray summary tasks``)."""
    by_name: Dict[str, collections.Counter] = collections.defaultdict(
        collections.Counter)
    for e in list_tasks():
        by_name[e.get("name", "?")][e.get("state", "?")] += 1
    return {name: dict(states) for name, states in by_name.items()}


def summarize_actors() -> Dict[str, Dict]:
    by_class: Dict[str, collections.Counter] = collections.defaultdict(
        collections.Counter)
    for a in list_actors():
        by_class[a.get("class_name", "?")][a.get("state", "?")] += 1
    return {cls: dict(states) for cls, states in by_class.items()}
