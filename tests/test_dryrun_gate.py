"""The multichip dryrun gate must fail LOUDLY, not silently shrink
(VERDICT r3 weak #6 / next-round #10): if JAX initialized its backend
before `_ensure_virtual_devices` could plant the virtual-device flags, the
gate raises instead of quietly running on fewer devices.

Root-caused standalone-order flake (ISSUE 11): the subprocess used to pin
the 1-device backend with ``jax.config.update('jax_num_cpu_devices', 1)``,
an option this image's jax (0.4.x) does not have — the subprocess died on
AttributeError BEFORE the gate ran, so the expected "could not provision"
never appeared. It "passed" in tier-1 only because the file was never in
the fast tier (deselected by ``-m 'not slow'``). The pinning is now
version-portable (XLA_FLAGS device count for 0.4.x, the config option
where it exists — the same ladder as ``__graft_entry__``'s
``_set_local_cpu_devices``) and the file rides the FAST tier so tier-1
actually exercises the gate.
"""

import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_ensure_virtual_devices_fails_loudly_when_backend_preinitialized():
    code = (
        "import os\n"
        # pin a 1-device CPU backend portably: 0.4.x jaxlibs only honor
        # the XLA_FLAGS count; newer ones also expose the config option
        "os.environ['XLA_FLAGS'] = ('--xla_force_host_platform_"
        "device_count=1 ' + os.environ.get('XLA_FLAGS', '')).strip()\n"
        "import jax\n"
        "jax.config.update('jax_platforms', 'cpu')\n"
        "try:\n"
        "    jax.config.update('jax_num_cpu_devices', 1)\n"
        "except (AttributeError, ValueError):\n"
        "    pass\n"
        "assert len(jax.devices()) == 1  # backend now initialized at 1\n"
        "import __graft_entry__ as g\n"
        "g._ensure_virtual_devices(8)\n"
    )
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env.pop("XLA_FLAGS", None)
    proc = subprocess.run([sys.executable, "-c", code], env=env, cwd=REPO,
                          capture_output=True, text=True, timeout=300)
    assert proc.returncode != 0, (
        "gate silently accepted a 1-device backend:\n" + proc.stdout)
    assert "could not provision" in (proc.stdout + proc.stderr), (
        "subprocess failed before the gate could run:\n"
        + proc.stdout + proc.stderr)
