"""R2D2 — Recurrent Replay Distributed DQN (reference:
rllib/algorithms/r2d2/r2d2.py R2D2Config + r2d2_torch_policy.py loss;
Kapturowski et al. 2019).

The three R2D2 mechanics, TPU-first:
- **Stored recurrent state**: env runners carry the LSTM (h, c) across
  steps and record each fragment's starting state; replay resumes the net
  from that state instead of zeros (``SequenceReplayBuffer``).
- **Burn-in**: the first ``burn_in`` steps of every replayed sequence run
  forward only to warm the state (``lax.stop_gradient`` on the carry);
  the loss covers the remaining unroll.
- **Value rescaling**: targets use h(x) = sign(x)(√(|x|+1)−1) + εx and
  its inverse, stabilizing bootstrap magnitudes across reward scales.

The whole sequence update — burn-in scan, double-DQN targets along the
unroll, Huber loss, adam — is ONE jitted function over [B, T] batches;
the LSTM unroll is a ``lax.scan`` (one compiled cell regardless of T).
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax

import ray_tpu
from ray_tpu.rllib.algorithms.algorithm import Algorithm
from ray_tpu.rllib.algorithms.algorithm_config import AlgorithmConfig
from ray_tpu.rllib.core.learner import Learner
from ray_tpu.rllib.models.catalog import _mlp_forward, _mlp_params
from ray_tpu.rllib.utils.replay_buffer import SequenceReplayBuffer


def h_rescale(x: jnp.ndarray, eps: float = 1e-3) -> jnp.ndarray:
    return jnp.sign(x) * (jnp.sqrt(jnp.abs(x) + 1.0) - 1.0) + eps * x


def h_inverse(x: jnp.ndarray, eps: float = 1e-3) -> jnp.ndarray:
    # closed-form inverse of h_rescale (Kapturowski 2019 appendix)
    num = jnp.sqrt(1.0 + 4.0 * eps * (jnp.abs(x) + 1.0 + eps)) - 1.0
    return jnp.sign(x) * (jnp.square(num / (2.0 * eps)) - 1.0)


# ------------------------------------------------------------------- module
@dataclasses.dataclass
class R2D2ModuleSpec:
    """Recurrent Q-network spec (reference: r2d2 + recurrent_net.py)."""

    obs_dim: int
    action_dim: int
    discrete: bool = True
    hiddens: Tuple[int, ...] = (64,)
    lstm_cell_size: int = 64
    dueling: bool = True

    def build(self) -> "R2D2Module":
        return R2D2Module(self)


class R2D2Module:
    """Encoder MLP → LSTM → (dueling) Q heads. The recurrent interface
    (initial_state / explore_action_recurrent) plugs into the env runner's
    stateful path; q_seq is the learner's scan over stored sequences."""

    def __init__(self, spec: R2D2ModuleSpec):
        self.spec = spec
        self._act = jax.nn.relu
        self.cell_size = spec.lstm_cell_size

    def init(self, rng) -> Dict:
        k1, k2, k3, k4, k5 = jax.random.split(rng, 5)
        enc_sizes = (self.spec.obs_dim, *self.spec.hiddens)
        H, E = self.cell_size, enc_sizes[-1]
        scale = jnp.sqrt(1.0 / (E + H))
        params = {
            "enc": _mlp_params(k1, enc_sizes, final_scale=1.0),
            "lstm": {
                "wx": jax.random.normal(k2, (E, 4 * H)) * scale,
                "wh": jax.random.normal(k3, (H, 4 * H)) * scale,
                "b": jnp.zeros((4 * H,)),
            },
            "adv": _mlp_params(k4, (H, self.spec.action_dim)),
            # exploration epsilon rides in params (no recompilation on
            # schedule updates — same pattern as DQNModule)
            "epsilon": jnp.asarray(1.0, jnp.float32),
        }
        if self.spec.dueling:
            params["v"] = _mlp_params(k5, (H, 1))
        return params

    def initial_state(self, batch_size: int) -> Tuple:
        return (jnp.zeros((batch_size, self.cell_size)),
                jnp.zeros((batch_size, self.cell_size)))

    def _encode(self, params, obs):
        x = obs
        for layer in params["enc"]:
            x = self._act(x @ layer["w"] + layer["b"])
        return x

    def _cell(self, params, x, state):
        h, c = state
        gates = x @ params["lstm"]["wx"] + h @ params["lstm"]["wh"] \
            + params["lstm"]["b"]
        i, f, g, o = jnp.split(gates, 4, axis=-1)
        c = jax.nn.sigmoid(f + 1.0) * c + jax.nn.sigmoid(i) * jnp.tanh(g)
        h = jax.nn.sigmoid(o) * jnp.tanh(c)
        return h, (h, c)

    def _q_from_h(self, params, h):
        adv = _mlp_forward(params["adv"], h, self._act)
        if self.spec.dueling:
            v = _mlp_forward(params["v"], h, self._act)
            return v + adv - adv.mean(axis=-1, keepdims=True)
        return adv

    def q_seq(self, params, obs_seq, state, reset_mask=None):
        """obs_seq [T, B, obs] + (h, c) → (q [T, B, A], final_state).

        reset_mask [T, B] (optional): rows where the carry is zeroed
        BEFORE consuming step t — replayed sequences spanning episode
        boundaries must reset the state exactly where the env runner did
        at collection time, or post-boundary targets train on hidden
        state inference never sees."""
        enc = self._encode(params, obs_seq)

        if reset_mask is None:
            def step(carry, x):
                h, new_carry = self._cell(params, x, carry)
                return new_carry, h

            final_state, hs = jax.lax.scan(step, state, enc)
        else:
            def step(carry, xs):
                x, reset = xs
                keep = (1.0 - reset)[:, None]
                carry = tuple(c * keep for c in carry)
                h, new_carry = self._cell(params, x, carry)
                return new_carry, h

            final_state, hs = jax.lax.scan(step, state, (enc, reset_mask))
        return self._q_from_h(params, hs), final_state

    # ------------------------------------------- env-runner interfaces
    def explore_action_recurrent(self, params, obs, state, rng):
        """One stateful step: eps-greedy over Q(h)."""
        enc = self._encode(params, obs)
        h, new_state = self._cell(params, enc, state)
        q = self._q_from_h(params, h)
        greedy = jnp.argmax(q, axis=-1)
        k1, k2 = jax.random.split(rng)
        random_a = jax.random.randint(
            k1, greedy.shape, 0, self.spec.action_dim)
        explore = jax.random.uniform(k2, greedy.shape) < params["epsilon"]
        action = jnp.where(explore, random_a, greedy)
        zeros = jnp.zeros_like(q[..., 0])
        return action, zeros, q.max(axis=-1), new_state

    def forward(self, params, obs) -> Dict[str, jnp.ndarray]:
        """Stateless facade (zero state) for last-vf bootstraps and
        non-recurrent callers."""
        squeeze = obs.ndim == 1
        x = obs[None] if squeeze else obs
        enc = self._encode(params, x)
        h, _ = self._cell(params, enc, self.initial_state(x.shape[0]))
        q = self._q_from_h(params, h)
        out = {"logits": q, "vf": q.max(axis=-1)}
        if squeeze:
            out = {k: v[0] for k, v in out.items()}
        return out

    def explore_action(self, params, obs, rng):
        a, logp, vf, _ = self.explore_action_recurrent(
            params, obs, self.initial_state(obs.shape[0]), rng)
        return a, logp, vf


# ------------------------------------------------------------------ learner
class R2D2Learner(Learner):
    """Burn-in + double-DQN-along-the-unroll sequence loss
    (reference: r2d2_torch_policy.py r2d2_loss)."""

    def __init__(self, module_spec, config, use_mesh: bool = False):
        super().__init__(module_spec, config, use_mesh=use_mesh)
        self.target_params = jax.tree.map(jnp.copy, self.params)

    def loss(self, params, batch):
        cfg = self.config
        gamma = cfg.get("gamma", 0.99)
        burn_in = cfg.get("burn_in", 0)
        use_h = cfg.get("use_h_function", True)
        tp = batch["target_params"]

        # [B, T, ...] -> time-major [T, B, ...]
        obs = jnp.swapaxes(batch["obs"], 0, 1)
        actions = jnp.swapaxes(batch["actions"], 0, 1).astype(jnp.int32)
        rewards = jnp.swapaxes(batch["rewards"], 0, 1)
        dones = jnp.swapaxes(batch["dones"], 0, 1)
        valid = jnp.swapaxes(batch["valid"], 0, 1).astype(jnp.float32)
        state = tuple(batch["state_in"])
        # mirror collection-time behavior: the runner zeroes (h, c) on the
        # step after a done, so the replayed unroll must reset the carry at
        # the same positions (step t resets iff step t-1 terminated)
        resets = jnp.concatenate(
            [jnp.zeros_like(dones[:1]), dones[:-1]], axis=0)

        if burn_in > 0:
            # warm the state; no gradient through the burn-in prefix
            _, state_on = self.module.q_seq(
                params, obs[:burn_in], state, resets[:burn_in])
            state_on = jax.tree.map(jax.lax.stop_gradient, state_on)
            _, state_tgt = self.module.q_seq(
                tp, obs[:burn_in], state, resets[:burn_in])
            obs, actions = obs[burn_in:], actions[burn_in:]
            rewards = rewards[burn_in:]
            valid = valid[burn_in:]
            resets, dones = resets[burn_in:], dones[burn_in:]
        else:
            state_on = state_tgt = state

        q_online, _ = self.module.q_seq(params, obs, state_on, resets)
        q_target, _ = self.module.q_seq(tp, obs, state_tgt, resets)

        q_sa = jnp.take_along_axis(
            q_online, actions[..., None], axis=-1)[..., 0]       # [T,B]
        # double DQN along the unroll: online argmax at t+1, target eval
        a_star = jnp.argmax(q_online[1:], axis=-1)               # [T-1,B]
        q_next = jnp.take_along_axis(
            q_target[1:], a_star[..., None], axis=-1)[..., 0]
        if use_h:
            q_next = h_inverse(q_next)
        target = rewards[:-1] + gamma * (1.0 - dones[:-1]) * q_next
        if use_h:
            target = h_rescale(target)
            q_pred = q_sa[:-1]
        else:
            q_pred = q_sa[:-1]
        td = q_pred - jax.lax.stop_gradient(target)
        huber = jnp.where(jnp.abs(td) < 1.0, 0.5 * td ** 2,
                          jnp.abs(td) - 0.5)
        # the last step has no within-sequence successor; autoreset rows
        # are invalid. Terminal steps keep their loss even though their
        # successor row is an (invalid) autoreset step — done cuts the
        # bootstrap, so no successor is needed, and they carry the reward.
        mask = valid[:-1] * jnp.maximum(dones[:-1], valid[1:])
        loss = jnp.sum(huber * mask) / jnp.maximum(jnp.sum(mask), 1.0)
        return loss, {
            "td_error_mean": jnp.sum(jnp.abs(td) * mask)
            / jnp.maximum(jnp.sum(mask), 1.0),
            "qf_mean": jnp.mean(q_sa),
        }

    def _build_update(self):
        def update(params, opt_state, batch):
            (loss, metrics), grads = jax.value_and_grad(
                self.loss, has_aux=True)(params, batch)
            grads["epsilon"] = jnp.zeros_like(params["epsilon"])
            updates, opt_state = self.tx.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            metrics["total_loss"] = loss
            return params, opt_state, metrics

        return jax.jit(update)

    def update(self, batch: Dict[str, np.ndarray]) -> Dict[str, float]:
        batch = dict(batch)
        batch["target_params"] = self.target_params
        self.params, self.opt_state, metrics = self._update(
            self.params, self.opt_state, batch)
        return {k: float(v) for k, v in metrics.items()}

    def sync_target(self, tau: float = 1.0) -> None:
        self.target_params = jax.tree.map(
            lambda t, o: (1 - tau) * t + tau * o,
            self.target_params, self.params)

    def set_epsilon(self, eps: float) -> None:
        self.params["epsilon"] = jnp.asarray(eps, jnp.float32)

    def get_state(self) -> Dict:
        s = super().get_state()
        s["target_params"] = jax.device_get(self.target_params)
        return s

    def set_state(self, state: Dict) -> None:
        super().set_state(state)
        self.target_params = state["target_params"]


# ---------------------------------------------------------------- algorithm
class R2D2Config(AlgorithmConfig):
    def __init__(self, algo_class=None):
        super().__init__(algo_class=algo_class or R2D2)
        self.lr = 5e-4
        self.train_batch_size = 16          # sequences per update
        self.replay_buffer_capacity = 4000  # sequences
        self.num_steps_sampled_before_learning_starts = 200
        self.target_network_update_freq = 400  # env steps
        self.training_intensity = 4.0
        self.epsilon = [(0, 1.0), (5_000, 0.05)]
        self.burn_in = 4
        self.model = {"use_lstm": True, "lstm_cell_size": 64,
                      "hiddens": (64,)}
        self.rollout_fragment_length = 20   # burn_in + unroll
        self.num_env_runners = 1
        self.use_h_function = True

    def _training_keys(self):
        return {"replay_buffer_capacity", "target_network_update_freq",
                "num_steps_sampled_before_learning_starts", "epsilon",
                "burn_in", "training_intensity", "use_h_function"}

    def learner_config_dict(self) -> Dict:
        d = super().learner_config_dict()
        d.update({"burn_in": self.burn_in,
                  "use_h_function": self.use_h_function})
        return d

    def module_spec(self) -> R2D2ModuleSpec:
        base = super().module_spec()
        if not base.discrete:
            raise ValueError("R2D2 supports discrete action spaces only")
        return R2D2ModuleSpec(
            obs_dim=base.obs_dim, action_dim=base.action_dim,
            hiddens=tuple(self.model.get("hiddens", (64,))),
            lstm_cell_size=int(self.model.get("lstm_cell_size", 64)),
            dueling=bool(self.model.get("dueling", True)))


class R2D2(Algorithm):
    learner_cls = R2D2Learner

    @classmethod
    def get_default_config(cls):
        return R2D2Config(algo_class=cls)

    def setup(self, _config) -> None:
        super().setup(_config)
        cfg = self.config
        self.replay = SequenceReplayBuffer(cfg.replay_buffer_capacity,
                                           seed=cfg.seed)
        self._steps_since_target_sync = 0

    def _make_runner(self, idx: int):
        cfg = self.config
        from ray_tpu.rllib.env.env_runner import SingleAgentEnvRunner

        return ray_tpu.remote(SingleAgentEnvRunner).options(
            resources={"CPU": 1}).remote(
                cfg.make_env(), cfg.num_envs_per_env_runner,
                cfg.rollout_fragment_length, self._module_spec,
                seed=cfg.seed + idx * 1000 + 1, explore=cfg.explore,
                gamma=cfg.gamma, connector=cfg.connector)

    def _epsilon_at(self, step: int) -> float:
        from ray_tpu.rllib.utils.schedules import piecewise_linear

        return piecewise_linear(self.config.epsilon, step)

    def training_step(self) -> Dict:
        cfg = self.config
        learner = self.learner_group.local_learner()
        learner.set_epsilon(self._epsilon_at(self._total_env_steps))
        weights_ref = ray_tpu.put(learner.get_weights())

        samples = self._sample_from_runners(weights_ref)
        new_steps = sum(s["env_steps"] for s in samples)
        for s in samples:
            self.replay.add_sequences(
                {"obs": s["obs"], "actions": s["actions"],
                 "rewards": s["rewards"], "dones": s["dones"],
                 "valid": s["valid"].astype(np.float32)},
                tuple(np.asarray(x) for x in s["state_in"]))

        metrics: Dict = {"env_steps_this_iter": new_steps}
        seq_len = cfg.rollout_fragment_length
        if len(self.replay) * seq_len < \
                cfg.num_steps_sampled_before_learning_starts:
            return metrics

        num_updates = max(1, int(new_steps * cfg.training_intensity
                                 / max(cfg.train_batch_size * seq_len, 1)))
        for _ in range(num_updates):
            batch = self.replay.sample(cfg.train_batch_size)
            # sampled sequences are [B, T, ...] already (buffer layout)
            metrics.update(learner.update(batch))
        self._steps_since_target_sync += new_steps
        if self._steps_since_target_sync >= cfg.target_network_update_freq:
            learner.sync_target()
            self._steps_since_target_sync = 0
        metrics["epsilon"] = self._epsilon_at(self._total_env_steps)
        return metrics
