"""Offline experience IO (reference: rllib/offline/ — json_reader.py
JsonReader of SampleBatch rows and json_writer.py; SURVEY §2.4 'offline
data (offline/ 4.8k)').

Format: JSONL, one flat transition batch per line with base64-packed
float32/int64 arrays — self-describing and appendable, loadable without
RLlib."""

from __future__ import annotations

import base64
import glob as globlib
import json
import os
from typing import Dict, Iterator, List, Optional

import numpy as np


def _pack(arr: np.ndarray) -> Dict:
    arr = np.asarray(arr)
    return {"dtype": str(arr.dtype), "shape": list(arr.shape),
            "data": base64.b64encode(np.ascontiguousarray(arr)).decode()}


def _unpack(obj: Dict) -> np.ndarray:
    raw = base64.b64decode(obj["data"])
    return np.frombuffer(raw, dtype=obj["dtype"]).reshape(obj["shape"])


class JsonWriter:
    """Append transition batches to ``<path>/output-<n>.jsonl``."""

    def __init__(self, path: str, max_file_size_rows: int = 100_000):
        self.path = path
        os.makedirs(path, exist_ok=True)
        self._file_idx = 0
        self._rows_in_file = 0
        self._max_rows = max_file_size_rows
        self._fh = None

    def _ensure_file(self):
        if self._fh is None or self._rows_in_file >= self._max_rows:
            if self._fh:
                self._fh.close()
                self._file_idx += 1
                self._rows_in_file = 0
            self._fh = open(os.path.join(
                self.path, f"output-{self._file_idx:04d}.jsonl"), "a")

    def write(self, batch: Dict[str, np.ndarray]) -> None:
        self._ensure_file()
        n = len(next(iter(batch.values())))
        self._fh.write(json.dumps(
            {k: _pack(v) for k, v in batch.items()}) + "\n")
        self._fh.flush()
        self._rows_in_file += n

    def close(self) -> None:
        if self._fh:
            self._fh.close()
            self._fh = None


class JsonReader:
    """Cycle through JSONL experience files, yielding row-batch dicts."""

    def __init__(self, inputs: str, shuffle: bool = True, seed: int = 0):
        if os.path.isdir(inputs):
            self.files = sorted(globlib.glob(os.path.join(inputs, "*.jsonl")))
        else:
            self.files = sorted(globlib.glob(inputs))
        if not self.files:
            raise FileNotFoundError(f"no offline data under {inputs!r}")
        self._rng = np.random.default_rng(seed)
        self.shuffle = shuffle
        self._batches: Optional[List[Dict[str, np.ndarray]]] = None
        self._full: Optional[Dict[str, np.ndarray]] = None
        self._cursor = 0  # sequential read position when shuffle=False

    def _load_all(self) -> List[Dict[str, np.ndarray]]:
        if self._batches is None:
            self._batches = []
            for path in self.files:
                with open(path) as f:
                    for line in f:
                        if line.strip():
                            obj = json.loads(line)
                            self._batches.append(
                                {k: _unpack(v) for k, v in obj.items()})
        return self._batches

    def concat_all(self) -> Dict[str, np.ndarray]:
        if self._full is None:  # files are immutable once read
            batches = self._load_all()
            keys = batches[0].keys()
            self._full = {k: np.concatenate([b[k] for b in batches])
                          for k in keys}
        return self._full

    def sample(self, batch_size: int) -> Dict[str, np.ndarray]:
        full = self.concat_all()
        n = len(next(iter(full.values())))
        if self.shuffle:
            idx = self._rng.integers(0, n, batch_size)
        else:  # cycle sequentially through the dataset
            idx = (self._cursor + np.arange(batch_size)) % n
            self._cursor = int((self._cursor + batch_size) % n)
        return {k: v[idx] for k, v in full.items()}

    def __iter__(self) -> Iterator[Dict[str, np.ndarray]]:
        yield from self._load_all()
