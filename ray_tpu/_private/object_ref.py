"""ObjectRef — a first-class future naming an immutable object.

Parity with the reference's ObjectRef (reference: ``python/ray/_raylet.pyx``
ObjectRef + ``src/ray/core_worker/reference_count.h``): the ref carries its
owner's address so any holder can resolve value/locations without a central
directory; serializing a ref into a task argument registers a borrow with the
owner; ``__del__`` decrements the owner's local count.
"""

from __future__ import annotations

from typing import Any, Dict, Optional

from ray_tpu._private.ids import ObjectID


class ObjectRef:
    __slots__ = ("_id", "_owner_addr", "_registered", "__weakref__")

    def __init__(self, object_id: ObjectID, owner_addr: Optional[Dict] = None,
                 _register: bool = True):
        self._id = object_id
        self._owner_addr = owner_addr or {}
        self._registered = False
        if _register:
            w = _get_worker()
            if w is not None:
                w.reference_counter.add_local_ref(self)
                self._registered = True

    # -- identity ------------------------------------------------------------
    def id(self) -> ObjectID:
        return self._id

    def binary(self) -> bytes:
        return self._id.binary()

    def hex(self) -> str:
        return self._id.hex()

    def owner_addr(self) -> Dict:
        return self._owner_addr

    def task_id(self):
        return self._id.task_id()

    def __hash__(self):
        return hash(self._id)

    def __eq__(self, other):
        return isinstance(other, ObjectRef) and other._id == self._id

    def __repr__(self):
        return f"ObjectRef({self._id.hex()})"

    # -- lifecycle -----------------------------------------------------------
    def __del__(self):
        try:
            if self._registered:
                w = _get_worker()
                if w is not None:
                    w.reference_counter.remove_local_ref(self)
        except BaseException:
            pass  # interpreter teardown

    def __reduce__(self):
        w = _get_worker()
        if w is not None:
            w.reference_counter.on_ref_serialized(self)
        return (_rebuild_ref, (self._id.binary(), self._owner_addr))

    # -- sugar ---------------------------------------------------------------
    def future(self):
        """Return a concurrent.futures.Future resolving to the value."""
        import concurrent.futures

        fut: concurrent.futures.Future = concurrent.futures.Future()
        w = _get_worker()

        def poll():
            try:
                fut.set_result(w.get([self], timeout=None)[0])
            except BaseException as e:  # noqa: BLE001
                fut.set_exception(e)

        import threading

        threading.Thread(target=poll, daemon=True).start()
        return fut

    def __await__(self):
        """Await support inside async actors."""
        import asyncio

        loop = asyncio.get_event_loop()
        w = _get_worker()

        def blocking():
            return w.get([self], timeout=None)[0]

        return loop.run_in_executor(None, blocking).__await__()


def _rebuild_ref(binary: bytes, owner_addr: Dict) -> "ObjectRef":
    ref = ObjectRef(ObjectID(binary), owner_addr, _register=False)
    w = _get_worker()
    if w is not None:
        w.reference_counter.on_ref_deserialized(ref)
        ref._registered = True
    return ref


def _get_worker():
    from ray_tpu._private import worker as worker_mod

    return worker_mod.global_worker
