"""RLlib tests (reference analog: rllib/tests + tuned_examples learning
checks — CartPole PPO must actually learn, SURVEY §4 tier 4)."""

import numpy as np
import pytest

import ray_tpu
from ray_tpu.rllib import PPO, PPOConfig, RLModuleSpec
from ray_tpu.rllib.core.learner import PPOLearner
from ray_tpu.rllib.utils.gae import compute_gae


@pytest.fixture(scope="module")
def ray4():
    if not ray_tpu.is_initialized():
        ray_tpu.init(num_cpus=4)
    yield
    ray_tpu.shutdown()


# --------------------------------------------------------------- unit tests
def test_gae_matches_manual():
    # single env, 3 steps, no dones
    rewards = np.array([[1.0], [1.0], [1.0]], np.float32)
    values = np.array([[0.5], [0.5], [0.5]], np.float32)
    dones = np.zeros((3, 1), np.float32)
    last_v = np.array([0.5], np.float32)
    adv, vt = compute_gae(rewards, values, dones, last_v,
                          gamma=0.9, lam=1.0)
    # delta_t = 1 + 0.9*0.5 - 0.5 = 0.95; lam=1 => discounted sums
    assert adv[2, 0] == pytest.approx(0.95)
    assert adv[1, 0] == pytest.approx(0.95 + 0.9 * 0.95)
    assert vt[0, 0] == pytest.approx(adv[0, 0] + 0.5)


def test_gae_cuts_at_done():
    rewards = np.ones((4, 1), np.float32)
    values = np.zeros((4, 1), np.float32)
    dones = np.array([[0.0], [1.0], [0.0], [0.0]], np.float32)
    adv, _ = compute_gae(rewards, values, dones, np.zeros(1, np.float32),
                         gamma=0.9, lam=1.0)
    # step 1 terminates: its advantage is just its reward
    assert adv[1, 0] == pytest.approx(1.0)
    # step 0 bootstraps from step 1 value but recursion restarts after done
    assert adv[0, 0] == pytest.approx(1.0 + 0.9 * 1.0)


def test_ppo_learner_moves_policy_toward_advantage():
    spec = RLModuleSpec(obs_dim=3, action_dim=2)
    lrn = PPOLearner(spec, {"lr": 0.01, "num_epochs": 10,
                            "minibatch_size": 128})
    rng = np.random.default_rng(0)
    obs = rng.normal(size=(128, 3)).astype(np.float32)
    # action 0 has positive advantage, action 1 negative (advantages are
    # standardized per minibatch, so they must vary to carry signal)
    actions = (np.arange(128) % 2).astype(np.int64)
    adv = np.where(actions == 0, 1.0, -1.0).astype(np.float32)
    out0 = lrn.module.forward(lrn.params, obs)
    batch = {"obs": obs, "actions": actions,
             "logp": np.asarray(lrn.module.dist.logp(
                 out0["logits"], actions)),
             "advantages": adv,
             "value_targets": np.zeros(128, np.float32)}
    zeros = np.zeros(128, np.int64)
    p0 = float(np.mean(np.exp(lrn.module.dist.logp(
        out0["logits"], zeros))))
    lrn.update(batch)
    out1 = lrn.module.forward(lrn.params, obs)
    p1 = float(np.mean(np.exp(lrn.module.dist.logp(
        out1["logits"], zeros))))
    assert p1 > p0, f"policy did not move toward advantage: {p0} -> {p1}"


def test_config_fluent_and_build(ray4):
    cfg = (PPOConfig()
           .environment("CartPole-v1")
           .env_runners(num_env_runners=1, num_envs_per_env_runner=2,
                        rollout_fragment_length=16)
           .training(lr=1e-3, train_batch_size=64, minibatch_size=32,
                     num_epochs=1, clip_param=0.3)
           .debugging(seed=7))
    assert cfg.clip_param == 0.3
    algo = cfg.build()
    try:
        result = algo.train()
        assert result["env_steps_this_iter"] >= 64
        assert "total_loss" in result
        assert result["training_iteration"] == 1
    finally:
        algo.stop()

    with pytest.raises(ValueError):
        PPOConfig().framework("torch")


def test_ppo_learns_cartpole(ray4):
    cfg = (PPOConfig()
           .environment("CartPole-v1")
           .env_runners(num_env_runners=2, num_envs_per_env_runner=8,
                        rollout_fragment_length=64)
           .training(lr=3e-4, train_batch_size=2048, minibatch_size=256,
                     num_epochs=6, entropy_coeff=0.01)
           .debugging(seed=0))
    algo = cfg.build()
    try:
        best = -np.inf
        for i in range(40):
            result = algo.train()
            best = max(best, result["episode_return_mean"])
            if best >= 150.0:
                break
        assert best >= 150.0, f"PPO failed to learn CartPole: best={best}"
        # inference helper: greedy action is valid
        act = algo.compute_single_action(np.zeros(4, np.float32))
        assert act in (0, 1)
    finally:
        algo.stop()


def test_checkpoint_restore(ray4, tmp_path):
    cfg = (PPOConfig()
           .environment("CartPole-v1")
           .env_runners(num_env_runners=1, num_envs_per_env_runner=2,
                        rollout_fragment_length=16)
           .training(train_batch_size=32, minibatch_size=32, num_epochs=1))
    algo = cfg.build()
    try:
        algo.train()
        d = str(tmp_path / "ckpt")
        import os

        os.makedirs(d, exist_ok=True)
        algo.save_checkpoint(d)
        w0 = algo.get_weights()
    finally:
        algo.stop()

    algo2 = cfg.copy().build()
    try:
        algo2.load_checkpoint(d)
        w1 = algo2.get_weights()
        np.testing.assert_allclose(
            np.asarray(w0["pi"][0]["w"]), np.asarray(w1["pi"][0]["w"]))
    finally:
        algo2.stop()


def test_env_runner_fault_tolerance(ray4):
    cfg = (PPOConfig()
           .environment("CartPole-v1")
           .env_runners(num_env_runners=2, num_envs_per_env_runner=2,
                        rollout_fragment_length=16)
           .training(train_batch_size=64, minibatch_size=32, num_epochs=1))
    algo = cfg.build()
    try:
        algo.train()
        # kill one runner; the next step must replace it and continue
        ray_tpu.kill(algo.env_runners[0])
        result = algo.train()
        assert result["env_steps_this_iter"] >= 32
        result = algo.train()
        assert result["env_steps_this_iter"] >= 64
    finally:
        algo.stop()


# ------------------------------------------------------------------- vtrace
def test_vtrace_on_policy_reduces_to_td_lambda_targets():
    """With target==behavior (rho=c=1), vs equals the lambda=1 TD targets."""
    import jax.numpy as jnp

    from ray_tpu.rllib.utils.vtrace import vtrace

    T, B = 4, 2
    rng = np.random.default_rng(0)
    logp = jnp.asarray(rng.normal(size=(T, B)).astype(np.float32))
    rewards = jnp.asarray(rng.normal(size=(T, B)).astype(np.float32))
    values = jnp.asarray(rng.normal(size=(T, B)).astype(np.float32))
    dones = jnp.zeros((T, B), jnp.float32)
    bootstrap = jnp.asarray(rng.normal(size=(B,)).astype(np.float32))
    vs, pg_adv = vtrace(logp, logp, rewards, values, dones, bootstrap,
                        gamma=0.9)
    # manual backward recursion with rho=c=1
    expect = np.zeros((T + 1, B), np.float32)
    expect[T] = np.asarray(bootstrap)
    v = np.asarray(values)
    r = np.asarray(rewards)
    for t in reversed(range(T)):
        expect[t] = r[t] + 0.9 * expect[t + 1]
    np.testing.assert_allclose(np.asarray(vs), expect[:T], rtol=1e-5)


def test_vtrace_clips_large_ratios():
    import jax.numpy as jnp

    from ray_tpu.rllib.utils.vtrace import vtrace

    T, B = 3, 1
    behavior = jnp.zeros((T, B))
    target = jnp.full((T, B), 5.0)  # huge ratio, must clip to 1
    rewards = jnp.ones((T, B))
    values = jnp.zeros((T, B))
    dones = jnp.zeros((T, B))
    vs_clipped, _ = vtrace(behavior, target, rewards, values, dones,
                           jnp.zeros(B), gamma=0.9, clip_rho=1.0, clip_c=1.0)
    vs_unit, _ = vtrace(behavior, behavior, rewards, values, dones,
                        jnp.zeros(B), gamma=0.9)
    np.testing.assert_allclose(np.asarray(vs_clipped), np.asarray(vs_unit),
                               rtol=1e-5)


# ------------------------------------------------------------ replay buffer
def test_replay_buffer_wraps_and_samples():
    from ray_tpu.rllib.utils.replay_buffer import ReplayBuffer

    buf = ReplayBuffer(capacity=10, seed=0)
    for start in range(0, 25, 5):
        buf.add_batch({"x": np.arange(start, start + 5, dtype=np.int64)})
    assert len(buf) == 10
    sample = buf.sample(32)
    assert sample["x"].min() >= 15  # oldest entries overwritten

def test_prioritized_replay_prefers_high_priority():
    from ray_tpu.rllib.utils.replay_buffer import PrioritizedReplayBuffer

    buf = PrioritizedReplayBuffer(capacity=100, alpha=1.0, seed=0)
    buf.add_batch({"x": np.arange(100, dtype=np.int64)})
    prios = np.full(100, 1e-6)
    prios[7] = 1000.0
    buf.update_priorities(np.arange(100), prios)
    sample = buf.sample(64)
    assert (sample["x"] == 7).mean() > 0.9
    assert "weights" in sample and "batch_indexes" in sample


# ---------------------------------------------------------------- DQN / SAC
def test_dqn_mechanics_and_checkpoint(ray4, tmp_path):
    from ray_tpu.rllib import DQNConfig

    cfg = (DQNConfig()
           .environment("CartPole-v1")
           .env_runners(num_env_runners=1, num_envs_per_env_runner=8,
                        rollout_fragment_length=16)
           .training(lr=1e-3, train_batch_size=64,
                     num_steps_sampled_before_learning_starts=200,
                     target_network_update_freq=256,
                     training_intensity=1.0, prioritized_replay=True))
    algo = cfg.build()
    try:
        for _ in range(4):
            result = algo.step()
        assert result["num_env_steps_sampled_lifetime"] >= 512
        assert np.isfinite(result["td_error_mean"])
        assert 0.0 <= result["epsilon"] <= 1.0
        d = str(tmp_path / "dqn_ckpt")
        import os

        os.makedirs(d, exist_ok=True)
        algo.save_checkpoint(d)
        learner = algo.learner_group.local_learner()
        w_before = np.asarray(learner.get_weights()["q"][0]["w"])
    finally:
        algo.stop()

    algo2 = cfg.copy().build()
    try:
        algo2.load_checkpoint(d)
        w_after = np.asarray(
            algo2.learner_group.local_learner().get_weights()["q"][0]["w"])
        np.testing.assert_allclose(w_before, w_after)
        # target params restored too
        assert algo2.learner_group.local_learner().target_params is not None
    finally:
        algo2.stop()


def test_sac_mechanics(ray4):
    from ray_tpu.rllib import SACConfig

    cfg = (SACConfig()
           .environment("Pendulum-v1")
           .env_runners(num_env_runners=1, num_envs_per_env_runner=4,
                        rollout_fragment_length=8)
           .training(train_batch_size=64,
                     num_steps_sampled_before_learning_starts=100,
                     training_intensity=0.25))
    algo = cfg.build()
    try:
        for _ in range(6):
            result = algo.step()
        assert np.isfinite(result["critic_loss"])
        assert np.isfinite(result["actor_loss"])
        assert result["alpha"] > 0
        # entropy target pull: alpha must have moved off its init
        assert abs(result["alpha"] - 1.0) > 1e-4
    finally:
        algo.stop()


def test_impala_async_mechanics(ray4):
    from ray_tpu.rllib import IMPALAConfig

    cfg = (IMPALAConfig()
           .environment("CartPole-v1")
           .env_runners(num_env_runners=2, num_envs_per_env_runner=4,
                        rollout_fragment_length=16)
           .training(lr=5e-4, num_fragments_per_step=4,
                     broadcast_interval=2))
    algo = cfg.build()
    try:
        r1 = algo.step()
        assert r1["num_fragments_consumed"] == 4
        assert r1["env_steps_this_iter"] == 4 * 16 * 4
        r2 = algo.step()
        assert np.isfinite(r2["policy_loss"])
        assert np.isfinite(r2["entropy"])
    finally:
        algo.stop()


# ------------------------------------------------------------- offline / BC
def test_json_offline_io_roundtrip(tmp_path):
    from ray_tpu.rllib.offline import JsonReader, JsonWriter

    w = JsonWriter(str(tmp_path))
    for i in range(3):
        w.write({"obs": np.random.rand(10, 4).astype(np.float32),
                 "actions": np.full(10, i, np.int64)})
    w.close()
    r = JsonReader(str(tmp_path))
    full = r.concat_all()
    assert full["obs"].shape == (30, 4)
    assert sorted(set(full["actions"])) == [0, 1, 2]
    sample = r.sample(16)
    assert sample["obs"].shape == (16, 4)


def test_bc_imitates_scripted_policy(ray4, tmp_path):
    """BC on a dataset from a deterministic scripted policy must reproduce
    that policy (reference: BC learning tests in rllib/algorithms/bc)."""
    from ray_tpu.rllib import BCConfig
    from ray_tpu.rllib.offline import JsonWriter

    rng = np.random.default_rng(0)
    obs = rng.normal(size=(2000, 4)).astype(np.float32)
    actions = (obs[:, 0] + obs[:, 2] > 0).astype(np.int64)  # scripted rule
    w = JsonWriter(str(tmp_path))
    for s in range(0, 2000, 500):
        w.write({"obs": obs[s:s + 500], "actions": actions[s:s + 500]})
    w.close()

    cfg = (BCConfig()
           .training(lr=3e-3, train_batch_size=256, num_epochs=2,
                     obs_dim=4, action_dim=2, discrete=True,
                     dataset_epochs_per_iter=2)
           .offline(offline_data=str(tmp_path)))
    algo = cfg.build()
    try:
        for _ in range(8):
            result = algo.step()
        assert np.isfinite(result["bc_loss"])
        # imitation accuracy on held-out states
        test_obs = rng.normal(size=(500, 4)).astype(np.float32)
        want = (test_obs[:, 0] + test_obs[:, 2] > 0).astype(np.int64)
        import jax.numpy as jnp

        module = algo._module_spec.build()
        out = module.forward(algo.get_weights(), jnp.asarray(test_obs))
        got = np.asarray(jnp.argmax(out["logits"], axis=-1))
        acc = (got == want).mean()
        assert acc > 0.9, f"BC accuracy {acc}"
    finally:
        algo.stop()
