"""CLI (reference: python/ray/scripts/scripts.py — ``ray
start/stop/status/memory/timeline/summary`` via click; argparse here).

``python -m ray_tpu.scripts.cli start --head`` daemonizes a head node whose
address lands in ``/tmp/ray_tpu_current_head``; workers join with
``start --address host:port``.
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import subprocess
import sys
import time

ADDR_FILE = "/tmp/ray_tpu_current_head"
PID_FILE = "/tmp/ray_tpu_node_pids"


def _record_pid(pid: int) -> None:
    pids = []
    if os.path.exists(PID_FILE):
        with open(PID_FILE) as f:
            pids = json.load(f)
    pids.append(pid)
    with open(PID_FILE, "w") as f:
        json.dump(pids, f)


def cmd_start(args) -> int:
    runner = (
        "import json, signal, sys, time\n"
        "import ray_tpu\n"
        "from ray_tpu._private.node import Node\n"
        f"head = {args.head}\n"
        f"addr = {args.address!r}\n"
        f"res = json.loads({args.resources!r}) if {args.resources!r} else None\n"
        f"num_cpus = {args.num_cpus!r}\n"
        "if num_cpus is not None:\n"
        "    res = dict(res or {}); res['CPU'] = float(num_cpus)\n"
        "if head:\n"
        f"    node = Node(head=True, head_port={args.port}, resources=res)\n"
        "else:\n"
        "    host, _, port = addr.partition(':')\n"
        "    node = Node(head=False, head_host=host, head_port=int(port),"
        " resources=res)\n"
        "node.start()\n"
        "if head:\n"
        f"    open({ADDR_FILE!r}, 'w').write("
        "f'{node.head_host}:{node.head_port}')\n"
        "print('NODE_READY', node.session_dir, flush=True)\n"
        "def _stop(*a):\n"
        "    node.stop(cleanup_session=head)\n"
        "    sys.exit(0)\n"
        "signal.signal(signal.SIGTERM, _stop)\n"
        "while True:\n"
        "    time.sleep(3600)\n"
    )
    proc = subprocess.Popen(
        [sys.executable, "-u", "-c", runner],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        start_new_session=True)
    deadline = time.monotonic() + 60
    while time.monotonic() < deadline:
        line = proc.stdout.readline()
        if line.startswith("NODE_READY"):
            _record_pid(proc.pid)
            print(f"node started (pid {proc.pid}): {line.split()[1]}")
            if args.head:
                with open(ADDR_FILE) as f:
                    print(f"head address: {f.read()}")
            return 0
        if proc.poll() is not None:
            print("node failed to start:\n" + line +
                  (proc.stdout.read() or ""))
            return 1
    proc.kill()
    print("node start timed out")
    return 1


def cmd_up(args) -> int:
    """Launch head + workers over SSH (or locally) from a cluster config
    (reference: autoscaler/_private/commands.py create_or_update_cluster)."""
    from ray_tpu.autoscaler.launcher import (
        ClusterLauncher, load_cluster_config)

    config = load_cluster_config(args.config)
    address = ClusterLauncher(config).up()
    print(f"cluster '{config.get('cluster_name', 'cluster')}' up; "
          f"head address: {address}")
    print(f"connect with: ray_tpu.init(address={address!r})")
    return 0


def cmd_down(args) -> int:
    from ray_tpu.autoscaler.launcher import (
        ClusterLauncher, load_cluster_config)

    config = load_cluster_config(args.config)
    ClusterLauncher(config).down()
    print(f"cluster '{config.get('cluster_name', 'cluster')}' down")
    return 0


def cmd_stop(args) -> int:
    from ray_tpu._private import lifecycle

    signalled = []
    if os.path.exists(PID_FILE):
        with open(PID_FILE) as f:
            pids = json.load(f)
        for pid in pids:
            try:
                os.killpg(os.getpgid(pid), signal.SIGTERM)
                signalled.append(pid)
            except (ProcessLookupError, PermissionError):
                pass
        os.remove(PID_FILE)
    for f in (ADDR_FILE,):
        if os.path.exists(f):
            os.remove(f)
    # wait (bounded) for the signalled runners to finish their graceful
    # node.stop — returning while their teardown is in flight would make
    # the post-stop `status` race its own cluster
    deadline = time.monotonic() + 15
    pending = list(signalled)
    while pending and time.monotonic() < deadline:
        pending = [p for p in pending if lifecycle._pid_alive(p)]
        if pending:
            time.sleep(0.1)
    for pid in pending:
        try:
            os.killpg(os.getpgid(pid), signal.SIGKILL)
        except (ProcessLookupError, PermissionError, OSError):
            pass
    print(f"stopped {len(signalled)} node(s)")
    # sweep sessions: with --all, kill live registered daemons too
    # (escalating SIGTERM→SIGKILL); otherwise just unlink session dirs
    # whose pids are all dead so their shm segments are reclaimed
    removed = lifecycle.gc_stale_sessions(
        kill_live=getattr(args, "all", False))
    print(f"reaped {len(removed)} session(s)")
    for path in removed:
        print(f"  {path}")
    return 0


def _connect():
    import ray_tpu

    if ray_tpu.is_initialized():
        return ray_tpu
    if not os.path.exists(ADDR_FILE):
        print("no running head (start one with: "
              "python -m ray_tpu.scripts.cli start --head)")
        sys.exit(1)
    with open(ADDR_FILE) as f:
        ray_tpu.init(address=f.read().strip())
    return ray_tpu


def cmd_status(args) -> int:
    # session lifecycle view first: it needs no running head, and "zero
    # live sessions" is the leak-gate signal benches/CI assert on
    from ray_tpu._private import lifecycle

    sessions = lifecycle.list_sessions()
    print(lifecycle.format_sessions(sessions))
    live = sum(1 for s in sessions if s["live"])
    print(f"\nlive sessions: {live}")
    if not os.path.exists(ADDR_FILE):
        import ray_tpu as _rt

        if not _rt.is_initialized():
            return 0
    try:
        ray_tpu = _connect()
        total = ray_tpu.cluster_resources()
        avail = ray_tpu.available_resources()
    except SystemExit:
        raise
    except Exception as e:
        # a stale ADDR_FILE (runner SIGKILL'd, machine rebooted) must not
        # turn the lifecycle view into a traceback — that headless view
        # is the whole point of `status` after a crash
        print(f"\n(head at {ADDR_FILE} unreachable: {type(e).__name__})")
        return 0
    print("\nNode status")
    print("-" * 40)
    for n in ray_tpu.nodes():
        state = "ALIVE" if n["alive"] else "DEAD"
        print(f"  {n['node_id'][:12]} {state}")
    print("\nResources")
    print("-" * 40)
    for k in sorted(total):
        used = total[k] - avail.get(k, 0.0)
        print(f"  {used:g}/{total[k]:g} {k}")
    _print_head_status()
    _print_events()
    _print_object_plane()
    _print_data_plane()
    _print_data_pipelines()
    _print_worker_pool()
    _print_direct_call_plane()
    return 0


def _print_object_plane() -> None:
    """Object ownership rollup (ISSUE 15): per-node store bytes by tier,
    cluster ref-table totals, and the leak watchdog's verdict."""
    try:
        from ray_tpu._private import worker as worker_mod

        w = worker_mod.global_worker
        st = w.head_call("ObjectSummary", {"group_by": "node"}, timeout=15)
    except Exception:
        return  # older head without the RPC, or a head mid-bounce
    nodes = st.get("nodes") or {}
    if not nodes:
        return
    print("\nObject plane")
    print("-" * 40)
    total_refs: dict = {}
    suspects = 0
    for node_id, nd in sorted(nodes.items()):
        if nd.get("error"):
            print(f"  {str(node_id)[:12]}: unreachable")
            continue
        tiers = nd.get("tiers") or {}
        store = nd.get("store") or {}
        suspects += len(nd.get("leak_suspects") or [])
        g = (st.get("groups") or {}).get(node_id) or {}
        for k, v in (g.get("refs") or {}).items():
            total_refs[k] = total_refs.get(k, 0) + v
        print(f"  {str(node_id)[:12]}: {_fmt_bytes(store.get('used', 0))}"
              f"/{_fmt_bytes(store.get('capacity', 0))} used   "
              f"tiers shm {tiers.get('shm_objects', 0)} / "
              f"disk {tiers.get('disk_objects', 0)} / "
              f"remote {tiers.get('remote_objects', 0)}")
    print(f"  refs: {total_refs.get('owned', 0)} owned, "
          f"{total_refs.get('borrowed', 0)} borrowed, "
          f"{total_refs.get('task_pins', 0)} task-pinned   "
          f"leak suspects {suspects}")


def _print_head_status() -> None:
    """Head-plane durability view: incarnation, uptime, WAL health, and
    what the last recovery reconciled dead (ISSUE 8)."""
    try:
        from ray_tpu._private import worker as worker_mod

        w = worker_mod.global_worker
        # explicit timeout caps the outage-queue budget too: status
        # against a down head answers in ~3s, not gcs_outage_queue_s
        st = w.head_call("GetHeadStatus", {}, timeout=3)
    except Exception:
        return  # older head without the RPC, or a head mid-bounce
    print("\nHead plane")
    print("-" * 40)
    print(f"  incarnation {st.get('incarnation', 1)}   "
          f"uptime {st.get('uptime_s', 0):.0f}s   "
          f"persist {st.get('persist') or '(memory only)'}")
    wal = st.get("wal")
    if wal:
        print(f"  WAL {wal['size_bytes']} B, seq {wal['seq']}, "
              f"last fsync {wal['last_fsync_age_s']:.1f}s ago, "
              f"{wal['fsyncs']} fsyncs")
    rec = st.get("last_recovery") or {}
    if rec:
        status = "closed" if rec.get("completed") else "open"
        print(f"  last recovery: restored {rec.get('restored_nodes', 0)} "
              f"nodes / {rec.get('restored_actors', 0)} actors / "
              f"{rec.get('restored_jobs', 0)} jobs; "
              f"reconciled dead {rec.get('reconciled_dead', 0)} "
              f"(window {status})")
    recv = st.get("recovering") or {}
    if any(recv.values()):
        print(f"  still recovering: {recv.get('nodes', 0)} nodes, "
              f"{recv.get('actors', 0)} actors, "
              f"{recv.get('jobs', 0)} jobs")


def _print_events() -> None:
    """Flight-recorder health (ISSUE 14): head ring occupancy plus
    per-node recorded/clipped/flushed counters."""
    try:
        from ray_tpu._private import worker as worker_mod

        w = worker_mod.global_worker
        st = w.head_call("GetEventStats", {}, timeout=3)
    except Exception:
        return  # older head without the RPC, or a head mid-bounce
    head = st.get("head") or {}
    nodes = st.get("nodes") or {}
    print("\nEvents")
    print("-" * 40)
    print(f"  head ring: {head.get('task_events_buffered', 0)} task events"
          f" / {head.get('spans_buffered', 0)} spans buffered"
          f" ({head.get('spans_dropped', 0)} dropped)")
    if not nodes:
        print("  (no flight-recorder flushes — task_event_sample_rate=0?)")
    for node_id, n in sorted(nodes.items()):
        print(f"  {str(node_id)[:12]}: recorded {n.get('recorded', 0)} "
              f"(clipped {n.get('clipped', 0)}) / flushed "
              f"{n.get('spans', 0)} spans + {n.get('events', 0)} events "
              f"in {n.get('flushes', 0)} flushes "
              f"({n.get('rings', 0)} rings, last "
              f"{n.get('last_flush_age_s', 0)}s ago)")


def _print_data_plane() -> None:
    """Device object plane view (ISSUE 9): this node's zero-copy puts,
    pull/relay counters and spill tiers, plus the head's broadcast-tree
    registry."""
    try:
        from ray_tpu._private import worker as worker_mod

        w = worker_mod.global_worker
        stats = w._acall(w.agent.call("GetPullStats", {}, timeout=3),
                         timeout=5)
    except Exception:
        return  # older agent without the RPC, or headless
    print("\nData plane (this node)")
    print("-" * 40)
    print(f"  zero-copy puts {stats.get('zero_copy_puts', 0)}   "
          f"pulls ok {stats.get('transfers_ok', 0)}   "
          f"chunks served {stats.get('chunks_served', 0)}")
    print(f"  bcast: depth {stats.get('bcast_tree_depth', 0)}, "
          f"tree pulls {stats.get('bcast_tree_pulls', 0)}, "
          f"relayed {stats.get('bcast_relay_bytes', 0)} B, "
          f"reparents {stats.get('bcast_reparents', 0)}, "
          f"fallbacks {stats.get('bcast_fallbacks', 0)}")
    spill = stats.get("spill") or {}
    if spill:
        print(f"  spill tiers: shm {spill.get('shm_objects', 0)} / "
              f"disk {spill.get('disk_objects', 0)} "
              f"({spill.get('disk_bytes', 0)} B) / "
              f"remote {spill.get('remote_objects', 0)}"
              f"  [restores {spill.get('num_restores', 0)}, "
              f"demotions {spill.get('num_remote_demotions', 0)}]")
    try:
        bs = w.head_call("BcastStats", {}, timeout=3)
        if bs and bs.get("trees"):
            print(f"  head trees: {bs['trees']} active, "
                  f"{bs.get('joins_total', 0)} joins, "
                  f"{bs.get('reparents_total', 0)} reparents")
    except Exception:
        pass


def _print_data_pipelines() -> None:
    """Streaming-shuffle / pipeline counters of the most recent Dataset
    execution (ISSUE 12): drivers publish ExecutorStats to the head KV
    (``__data_stats__:``), so status works from any process."""
    try:
        import json as _json

        from ray_tpu.experimental.internal_kv import (
            _internal_kv_get, _internal_kv_list)

        keys = sorted(_internal_kv_list(b"__data_stats__:"))
        if not keys:
            return
        st = _json.loads(_internal_kv_get(keys[-1]))
    except Exception:
        return
    print("\nData pipelines (last run)")
    print("-" * 40)
    print(f"  wall {st.get('wall_s', 0):.2f}s   "
          f"scheduler iters {st.get('loop_iters', 0)} "
          f"({st.get('idle_waits', 0)} idle waits)   "
          f"consumer stall {st.get('consumer_stall_s', 0):.3f}s over "
          f"{st.get('blocks_consumed', 0)} blocks")
    for op in st.get("ops", []):
        ex = op.get("extra") or {}
        if "shuffle_maps" not in ex:
            continue
        print(f"  shuffle {op.get('name')}: "
              f"{ex.get('shuffle_maps', 0)} maps -> "
              f"{ex.get('shuffle_reducers', 0)} reducers, "
              f"{ex.get('shuffle_shard_bytes', 0)} shard B "
              f"(peak in-flight {ex.get('shuffle_inflight_peak_bytes', 0)})")
        print(f"    stall fraction "
              f"{ex.get('shuffle_stall_fraction', 0):.2f}, "
              f"overlapped={ex.get('shuffle_reduce_overlapped_maps')}, "
              f"map re-execs {ex.get('shuffle_map_reexecs', 0)}, "
              f"reduce retries {ex.get('shuffle_reduce_retries', 0)}")


def _print_direct_call_plane() -> None:
    """Multiplexed direct-call plane view (ISSUE 11): this process's mux
    sessions/streams and shm-lane counters (each process keeps its own —
    the numbers here are the status driver's, plus the node agent's
    demand-paged pool view below)."""
    try:
        from ray_tpu._private import worker as worker_mod
        from ray_tpu._private.mux import MUX_STATS
        from ray_tpu._private.shm_rpc import SHM_STATS

        w = worker_mod.global_worker
        sessions = len(w._mux_pool._sessions)
        streams = w._mux_pool.total_streams()
        shm_sessions = w._mux_pool.shm_sessions()
    except Exception:
        return
    print("\nDirect-call plane (this process)")
    print("-" * 40)
    print(f"  mux sessions {sessions} ({shm_sessions} shm-attached)   "
          f"streams {streams}   "
          f"opened {MUX_STATS['streams_opened']} / "
          f"closed {MUX_STATS['streams_closed']}")
    print(f"  shm frames out {SHM_STATS['calls_out']} "
          f"({SHM_STATS['bytes_out']} B) / in {SHM_STATS['frames_in']} "
          f"({SHM_STATS['bytes_in']} B)")
    print(f"  fallbacks: oversize {SHM_STATS['fallback_oversize']}, "
          f"ring-full {SHM_STATS['fallback_ring_full']}   "
          f"attach ok {SHM_STATS['attach_ok']} / "
          f"declined {SHM_STATS['attach_declined']}   "
          f"order-gap flushes {SHM_STATS['order_gap_flushes']}")


def _fmt_hist(hist) -> str:
    if not hist:
        return "-"
    def key(k):
        return int(str(k).rstrip("+"))
    return " ".join(f"{k}:{hist[k]}" for k in sorted(hist, key=key))


def _print_worker_pool() -> None:
    """Warm worker pool + batched control-RPC view (ISSUE 10): pool
    level vs target, hit ratio of actor starts served warm, and the
    lease/registration batch-size histograms."""
    try:
        from ray_tpu._private import worker as worker_mod

        w = worker_mod.global_worker
        st = w._acall(w.agent.call("GetWorkerPoolStats", {}, timeout=3),
                      timeout=5)
    except Exception:
        return  # older agent without the RPC, or headless
    print("\nWorker pool (this node)")
    print("-" * 40)
    hits, misses = st.get("hits", 0), st.get("misses", 0)
    demand = st.get("demand_hits", 0)
    served = hits + demand
    ratio = served / (served + misses) if served + misses else 0.0
    print(f"  warm {st.get('warm', 0)}/{st.get('warm_target', 0)}   "
          f"idle {st.get('idle', 0)}   workers {st.get('workers', 0)}   "
          f"starting {st.get('starting', 0)}   "
          f"waiters {st.get('waiters', 0)}")
    print(f"  actor starts: {hits} warm hits + {demand} demand-paged / "
          f"{misses} cold forks (hit ratio {ratio:.0%})   "
          f"refills {st.get('refills', 0)}   "
          f"ttl-reaped {st.get('reaped', 0)}")
    print(f"  lease batch sizes: {_fmt_hist(st.get('lease_batch_hist'))}")
    print(f"  ready batch sizes: {_fmt_hist(st.get('ready_batch_hist'))}")


def cmd_list(args) -> int:
    from ray_tpu.util import state as state_api

    _connect()
    fn = {
        "actors": state_api.list_actors,
        "nodes": state_api.list_nodes,
        "tasks": state_api.list_tasks,
        "placement-groups": state_api.list_placement_groups,
        "jobs": state_api.list_jobs,
        "workers": state_api.list_workers,
        "objects": state_api.list_objects,
    }[args.resource]
    print(json.dumps(fn(), indent=1, default=str))
    return 0


def cmd_summary(args) -> int:
    from ray_tpu.util import state as state_api

    _connect()
    fn = {"tasks": state_api.summarize_tasks,
          "actors": state_api.summarize_actors,
          "objects": state_api.summarize_objects}[args.resource]
    print(json.dumps(fn(), indent=1))
    return 0


def cmd_timeline(args) -> int:
    if getattr(args, "session", ""):
        # post-mortem mode: no cluster needed — parse the crash-durable
        # ring files straight off the session dir (DaemonKiller / kill -9
        # debugging: the rings of dead processes are still there)
        from ray_tpu._private.events import recover_session, to_chrome_trace

        rings = recover_session(args.session)
        spans = [sp for ring in rings for sp in ring["spans"]]
        events = to_chrome_trace(spans)
        src = f"{len(rings)} ring file(s)"
    else:
        ray_tpu = _connect()
        events = ray_tpu.timeline()
        src = "head"
    path = args.output or f"/tmp/ray_tpu_timeline_{int(time.time())}.json"
    with open(path, "w") as f:
        json.dump(events, f)
    print(f"wrote {len(events)} events ({src}) to {path} "
          "(open in chrome://tracing or Perfetto)")
    return 0


def cmd_trace(args) -> int:
    """Span tree of one task across driver/agent/worker (ISSUE 14):
    resolves the task id (hex prefix) against the head's span ring and
    prints every span sharing its trace, nested by parent."""
    from ray_tpu._private.events import format_trace_tree

    ray_tpu = _connect()
    from ray_tpu._private import worker as worker_mod

    w = worker_mod.global_worker
    w.flush_task_events(wait=True)
    hits = w.head_call("ListSpans", {"task": args.task_id, "limit": 1000},
                       timeout=10)
    if not hits:
        print(f"no spans for task {args.task_id!r} (is "
              "task_event_sample_rate > 0, and did the task run "
              "recently?)")
        return 1
    traces = {sp["trace"] for sp in hits}
    for tr in sorted(traces):
        spans = w.head_call("ListSpans", {"trace": tr, "limit": 10000},
                            timeout=10)
        print(f"trace {tr:x} ({len(spans)} spans)")
        print(format_trace_tree(spans))
    return 0


def _fmt_bytes(n) -> str:
    n = float(n or 0)
    for unit in ("B", "KiB", "MiB", "GiB", "TiB"):
        if abs(n) < 1024 or unit == "TiB":
            return f"{n:.1f} {unit}" if unit != "B" else f"{int(n)} B"
        n /= 1024
    return f"{n:.1f} TiB"


def cmd_memory(args) -> int:
    """Cluster memory debugger (ISSUE 15; reference: ``ray memory``):
    every owned byte in the object plane attributed to the callsite /
    task that created it, plus the leak watchdog's current suspects."""
    from ray_tpu.util import state as state_api

    _connect()
    group_by = args.group_by
    out = state_api.object_summary(group_by=group_by,
                                   detail=group_by in ("callsite", "creator"),
                                   limit=args.limit)
    nodes = out.get("nodes") or {}
    total_used = sum((nd.get("store") or {}).get("used", 0)
                     for nd in nodes.values())
    total_objs = sum((nd.get("store") or {}).get("num_objects", 0)
                     for nd in nodes.values())
    attr = out.get("attribution") or {}
    print(f"Object store: {_fmt_bytes(total_used)} used across "
          f"{len(nodes)} node(s), {total_objs} sealed object(s); "
          f"{attr.get('ratio', 0):.0%} of copies attributed to a "
          f"creating callsite/task")
    for node_id, nd in sorted(nodes.items()):
        if nd.get("error"):
            print(f"  {node_id[:12]}: unreachable ({nd['error']})")
            continue
        tiers = nd.get("tiers") or {}
        lin = nd.get("lineage") or {}
        print(f"  {node_id[:12]}: "
              f"shm {_fmt_bytes(tiers.get('shm_bytes', 0))} "
              f"({tiers.get('shm_objects', 0)}) / "
              f"disk {_fmt_bytes(tiers.get('disk_bytes', 0))} "
              f"({tiers.get('disk_objects', 0)}) / "
              f"remote {tiers.get('remote_objects', 0)}   "
              f"processes {nd.get('num_processes', 0)}   "
              f"lineage {lin.get('records', 0)} rec "
              f"({_fmt_bytes(lin.get('bytes', 0))}), "
              f"{lin.get('reconstructions', 0)} replayed, "
              f"{lin.get('evictions', 0)} evicted")

    groups = out.get("groups") or {}
    sort_key = {"bytes": "total_bytes", "count": "count"}[args.sort_by]
    ordered = sorted(groups.items(),
                     key=lambda kv: kv[1].get(sort_key, 0), reverse=True)
    print(f"\nGrouped by {group_by} (top {args.limit}, by {args.sort_by})")
    print("-" * 72)
    if group_by in ("callsite", "creator"):
        # LINEAGE = how many of the group's objects the owner can rebuild
        # by chained task replay if a copy is lost (ISSUE 17)
        print(f"{'BYTES':>12} {'COUNT':>6} {'LOCAL':>6} {'BORROW':>6} "
              f"{'PINS':>5} {'LINEAGE':>7} {group_by.upper()}")
        for name, g in ordered[:args.limit]:
            print(f"{_fmt_bytes(g['total_bytes']):>12} {g['count']:>6} "
                  f"{g.get('local_refs', 0):>6} {g.get('borrowers', 0):>6} "
                  f"{g.get('task_pins', 0):>5} {g.get('lineage', 0):>7} "
                  f"{name}")
    else:
        print(f"{'BYTES':>12} {'COUNT':>6} {group_by.upper()}")
        for name, g in ordered[:args.limit]:
            print(f"{_fmt_bytes(g['total_bytes']):>12} {g['count']:>6} "
                  f"{name}")
    if not ordered:
        print("  (no objects)")

    if args.leaks:
        print("\nLeak suspects")
        print("-" * 72)
        any_suspect = False
        scans = 0
        for node_id, nd in sorted(nodes.items()):
            scans = max(scans, nd.get("leak_scans", 0))
            for s in nd.get("leak_suspects") or []:
                any_suspect = True
                print(f"  {node_id[:12]} {s['object_id'][:16]} "
                      f"{_fmt_bytes(s.get('size_bytes', 0)):>12} "
                      f"{s.get('reason'):<18} age {s.get('age_s', 0)}s  "
                      f"{s.get('callsite') or s.get('creator') or ''}")
        if not any_suspect:
            armed = scans > 0
            print("  none" + ("" if armed else
                              " (watchdog disarmed — set "
                              "RAY_TPU_OBJECT_LEAK_SCAN_INTERVAL_S > 0 "
                              "on node start to arm it)"))
    return 0


def cmd_train_resume(args) -> int:
    """Elastic-training recovery report (ISSUE 20): every
    ``train_resume::`` span the flight recorder holds, grouped per
    restart incarnation — how long teardown, group re-form, restore
    dispatch, and time-to-first-result each took."""
    if getattr(args, "session", ""):
        # post-mortem: parse ring files off the session dir (the driver
        # that recorded the resume may itself be gone)
        from ray_tpu._private.events import recover_session

        rings = recover_session(args.session)
        from ray_tpu._private.events import _span_dict

        spans = []
        for ring in rings:
            for sp in ring["spans"]:
                spans.append(sp if isinstance(sp, dict) else _span_dict(sp))
    else:
        _connect()
        from ray_tpu._private import worker as worker_mod

        w = worker_mod.global_worker
        w.flush_task_events(wait=True)
        spans = w.head_call("ListSpans", {"limit": 20000}, timeout=10) or []

    resumes = [sp for sp in spans
               if str(sp.get("name", "")).startswith("train_resume::")]
    if not resumes:
        print("no train_resume:: spans recorded (no elastic restart "
              "happened, or task_event_sample_rate is 0)")
        return 1

    # driver-side spans carry the restart ordinal; teardown is recorded
    # against the failing incarnation, the rest against the new one —
    # the ordinal, not the trace id, is the incarnation key. Worker-side
    # restore spans live in the workers' own traces; shown separately.
    by_restart: dict = {}
    worker_restores = []
    for sp in resumes:
        ex = sp.get("extra") or {}
        if sp["name"] == "train_resume::restore":
            worker_restores.append(sp)
        else:
            by_restart.setdefault(ex.get("restart"), []).append(sp)

    print(f"{len(by_restart)} recovery incarnation(s)")
    for restart in sorted(by_restart, key=lambda r: (r is None, r)):
        group = by_restart[restart]
        print(f"\nrestart #{restart if restart is not None else '?'}")
        for suffix, label in (
                ("teardown", "tear down failed group"),
                ("group_start", "re-form worker group"),
                ("start_training", "dispatch + in-store restore"),
                ("first_result", "first post-resume result"),
                ("total", "total time-to-resume")):
            for sp in group:
                if sp["name"] != f"train_resume::{suffix}":
                    continue
                ex = sp.get("extra") or {}
                notes = " ".join(f"{k}={v}" for k, v in sorted(ex.items())
                                 if k not in ("task", "restart"))
                print(f"  {label:<28} {sp.get('dur_us', 0) / 1e6:>8.3f}s"
                      f"  {notes}")
    if worker_restores:
        print("\nworker-side shard restores")
        for sp in sorted(worker_restores,
                         key=lambda s: ((s.get("extra") or {}).get("step", 0),
                                        (s.get("extra") or {}).get("rank", 0))):
            ex = sp.get("extra") or {}
            print(f"  rank {ex.get('rank', '?')} step {ex.get('step', '?')}"
                  f"  {sp.get('dur_us', 0) / 1e6:>8.3f}s"
                  f"  {ex.get('nbytes', 0)} B")
    return 0


def cmd_metrics(args) -> int:
    if getattr(args, "scrape", False) or getattr(args, "url", ""):
        # hit the head's HTTP scrape endpoint (metrics_export_port) the
        # way Prometheus would — proves the whole export path, not just
        # the in-process renderer
        import urllib.request

        url = args.url
        if not url:
            from ray_tpu._private import lifecycle

            for sess in lifecycle.list_sessions():
                port_file = os.path.join(sess["path"], "metrics_port")
                if sess["live"] and os.path.exists(port_file):
                    with open(port_file) as f:
                        url = f"http://127.0.0.1:{f.read().strip()}/metrics"
                    break
            if not url:
                print("no live session exports metrics "
                      "(set RAY_TPU_METRICS_EXPORT_PORT and restart the "
                      "head, or pass --url)")
                return 1
        with urllib.request.urlopen(url, timeout=10) as r:
            sys.stdout.write(r.read().decode())
        return 0
    from ray_tpu.util.metrics import prometheus_text

    _connect()
    print(prometheus_text())
    return 0


def cmd_serve_deploy(args) -> int:
    """Declarative deploy from a JSON config file (reference: `serve
    deploy config.yaml`; JSON here — no yaml dep in the image)."""
    import json as _json

    from ray_tpu import serve

    _connect()
    with open(args.config_file) as f:
        config = _json.load(f)
    serve.run_config(config)
    print(f"deployed {len(config.get('applications', []))} application(s)")
    return 0


def cmd_serve_status(args) -> int:
    import json as _json

    from ray_tpu import serve

    _connect()
    try:
        ctrl = serve._controller()
        routes = __import__("ray_tpu").get(
            ctrl.get_routes.remote(), timeout=30)
    except Exception:
        print(_json.dumps({"applications": {}}, indent=2))
        return 0
    out = {app: {**serve.status(app), "route_prefix": prefix}
           for prefix, (app, _ingress) in routes.items()}
    print(_json.dumps({"applications": out}, indent=2))
    return 0


def cmd_serve_shutdown(args) -> int:
    from ray_tpu import serve

    _connect()
    serve.shutdown()
    print("serve shut down")
    return 0


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="ray-tpu", description="ray_tpu cluster CLI")
    sub = p.add_subparsers(dest="cmd", required=True)

    s = sub.add_parser("start", help="start a head or worker node")
    s.add_argument("--head", action="store_true")
    s.add_argument("--address", default="")
    s.add_argument("--port", type=int, default=0)
    s.add_argument("--num-cpus", dest="num_cpus", default=None)
    s.add_argument("--resources", default="")
    s.set_defaults(fn=cmd_start)

    s = sub.add_parser("stop", help="stop all locally-started nodes")
    s.add_argument("--all", action="store_true",
                   help="also reap every registered session daemon and "
                        "remove session dirs/shm segments")
    s.set_defaults(fn=cmd_stop)

    s = sub.add_parser(
        "up", help="launch a cluster from a YAML/JSON config (ray up)")
    s.add_argument("config")
    s.set_defaults(fn=cmd_up)

    s = sub.add_parser("down", help="tear down a launched cluster")
    s.add_argument("config")
    s.set_defaults(fn=cmd_down)

    s = sub.add_parser("status", help="cluster resources + nodes")
    s.set_defaults(fn=cmd_status)

    s = sub.add_parser("list", help="list cluster state")
    s.add_argument("resource", choices=[
        "actors", "nodes", "tasks", "placement-groups", "jobs",
        "workers", "objects"])
    s.set_defaults(fn=cmd_list)

    s = sub.add_parser("summary", help="summarize tasks/actors")
    s.add_argument("resource", choices=["tasks", "actors", "objects"])
    s.set_defaults(fn=cmd_summary)

    s = sub.add_parser(
        "timeline",
        help="dump Perfetto/chrome-trace timeline (flight-recorder spans)")
    s.add_argument("--output", default="")
    s.add_argument("--session", default="",
                   help="offline mode: read ring files from this session "
                        "dir instead of a live head (post-mortem)")
    s.set_defaults(fn=cmd_timeline)

    s = sub.add_parser(
        "trace", help="print one task's cross-process span tree")
    s.add_argument("task_id", help="task id hex (prefix ok)")
    s.set_defaults(fn=cmd_trace)

    s = sub.add_parser(
        "memory",
        help="cluster memory debugger: store bytes attributed to the "
             "callsite/task that created them, plus leak suspects")
    s.add_argument("--group-by", dest="group_by", default="callsite",
                   choices=["node", "callsite", "creator", "tier"],
                   help="attribution axis: creating callsite "
                        "(module:qualname:line of the put()/.remote()), "
                        "creating task/actor, residency tier, or node")
    s.add_argument("--sort-by", dest="sort_by", default="bytes",
                   choices=["bytes", "count"],
                   help="order groups by total bytes (default) or count")
    s.add_argument("--leaks", action="store_true",
                   help="show the leak watchdog's current suspects "
                        "(requires object_leak_scan_interval_s > 0 on "
                        "the node agents)")
    s.add_argument("--limit", type=int, default=20,
                   help="rows per section (default 20)")
    s.set_defaults(fn=cmd_memory)

    s = sub.add_parser(
        "train-resume",
        help="elastic-training recovery report: per-restart "
             "teardown / re-form / restore / first-result timings "
             "from the train_resume:: flight-recorder spans")
    s.add_argument("--session", default="",
                   help="offline mode: read ring files from this session "
                        "dir instead of a live head (post-mortem)")
    s.set_defaults(fn=cmd_train_resume)

    s = sub.add_parser("metrics", help="Prometheus metrics dump")
    s.add_argument("--scrape", action="store_true",
                   help="GET the head's HTTP scrape endpoint instead of "
                        "rendering in-process")
    s.add_argument("--url", default="",
                   help="explicit scrape URL (implies --scrape)")
    s.set_defaults(fn=cmd_metrics)

    serve_p = sub.add_parser("serve", help="serve control")
    serve_sub = serve_p.add_subparsers(dest="serve_cmd", required=True)
    s = serve_sub.add_parser("deploy", help="deploy a JSON config")
    s.add_argument("config_file")
    s.set_defaults(fn=cmd_serve_deploy)
    s = serve_sub.add_parser("status", help="application status")
    s.set_defaults(fn=cmd_serve_status)
    s = serve_sub.add_parser("shutdown", help="tear serve down")
    s.set_defaults(fn=cmd_serve_shutdown)

    args = p.parse_args(argv)
    try:
        return args.fn(args)
    except BrokenPipeError:
        return 0


if __name__ == "__main__":
    sys.exit(main())
