"""Dynamic custom resources (reference: python/ray/experimental/
dynamic_resources.py set_resource — adjust a node's custom resource
capacity at runtime; used for quota-style admission control).

The agent owns the node's resource totals; this asks it to re-declare one,
which then gossips to the head and into scheduling decisions.
"""

from __future__ import annotations

from typing import Optional

import ray_tpu


def set_resource(resource_name: str, capacity: float,
                 node_id: Optional[str] = None) -> None:
    """Set a custom resource's total on a node (default: the local node).
    Capacity 0 deletes the resource."""
    from ray_tpu._private import worker as worker_mod

    w = worker_mod.global_worker
    if w is None or not w.connected:
        raise RuntimeError("ray_tpu.init() first")
    if resource_name in ("CPU", "GPU", "TPU", "memory"):
        raise ValueError(
            f"{resource_name} is a built-in resource; only custom "
            "resources can be set dynamically (reference restriction)")
    payload = {"resource": resource_name, "capacity": float(capacity)}
    if node_id is None or node_id == w.node_id:
        w._acall(w.agent.call("SetResource", payload), timeout=30)
        return
    # route to the target node's agent through the head's cluster view
    view = w._acall(w.head.call("GetClusterView", {}), timeout=30)
    info = view.get(node_id)
    if info is None:
        raise ValueError(f"no alive node {node_id!r}")
    from ray_tpu._private.config import CONFIG
    from ray_tpu._private.protocol import AsyncRpcClient

    async def call_remote():
        client = AsyncRpcClient()
        await client.connect_tcp(info["addr"]["host"], info["addr"]["port"])
        try:
            return await client.call("SetResource", payload,
                                      timeout=CONFIG.control_rpc_timeout_s)
        finally:
            # aclose, not close: close() leaves the cancelled read loop
            # un-awaited and the loop warns about it at teardown
            await client.aclose()

    w._acall(call_remote(), timeout=30)
