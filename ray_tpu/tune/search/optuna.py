"""OptunaSearch adapter (reference: python/ray/tune/search/optuna/
optuna_search.py). Gated: `optuna` is not in this image's baked package
set, so construction raises a clear ImportError; the adapter logic below
activates when optuna is importable."""

from __future__ import annotations

from typing import Dict, Optional

from ray_tpu.tune.search.sample import Categorical, Float, Integer
from ray_tpu.tune.search.searcher import Searcher


class OptunaSearch(Searcher):
    def __init__(self, space: Optional[Dict] = None,
                 metric: Optional[str] = None,
                 mode: Optional[str] = None, seed: int = 0, **kwargs):
        try:
            import optuna  # noqa: F401
        except ImportError as e:
            raise ImportError(
                "OptunaSearch requires `optuna`, which is not installed "
                "in this environment. Use BasicVariantGenerator (random/"
                "grid) or HyperOptSearch where available.") from e
        super().__init__(metric, mode)
        self._space = space or {}
        self._seed = seed
        self._trials: Dict[str, object] = {}
        self._completed = 0
        self._build()

    def _build(self) -> None:
        import optuna

        self._study = optuna.create_study(
            direction="maximize" if (self.mode or "max") == "max"
            else "minimize",
            sampler=optuna.samplers.TPESampler(seed=self._seed))

    def set_search_properties(self, metric, mode, config) -> bool:
        """Adopt the Tuner-supplied metric/mode/param_space (reference:
        optuna_search.py set_search_properties): the study's DIRECTION is
        baked at creation, so it must be rebuilt when mode/metric change
        — but only then, or when there is no history yet. Rebuilding
        whenever in-flight trials happened to be empty discarded the
        TPE sampler's accumulated observations between waves."""
        changed = (metric is not None and metric != self.metric) or \
            (mode is not None and mode != self.mode)
        super().set_search_properties(metric, mode, config)
        if config and not self._space:
            self._space = config
            changed = True
        if (changed or not self._completed) and not self._trials:
            self._build()
        return True

    def _suggest_param(self, ot, name, dom):
        if isinstance(dom, Categorical):
            return ot.suggest_categorical(name, list(dom.categories))
        if isinstance(dom, Integer):
            return ot.suggest_int(name, dom.lower, dom.upper - 1)
        if isinstance(dom, Float):
            if getattr(dom, "log", False):
                return ot.suggest_float(name, dom.lower, dom.upper, log=True)
            return ot.suggest_float(name, dom.lower, dom.upper)
        return dom  # constant

    def suggest(self, trial_id: str) -> Optional[Dict]:
        ot = self._study.ask()
        self._trials[trial_id] = ot
        return {k: self._suggest_param(ot, k, v)
                for k, v in self._space.items()}

    def on_trial_complete(self, trial_id, result=None,
                          error: bool = False) -> None:
        import optuna

        ot = self._trials.pop(trial_id, None)
        if ot is None:
            return
        self._completed += 1  # any logged outcome is optimizer history
        if error or not result or self.metric not in result:
            self._study.tell(ot, state=optuna.trial.TrialState.FAIL)
        else:
            self._study.tell(ot, float(result[self.metric]))
