"""Rule registry.

Each rule module exposes ``RULE_ID``, ``SUMMARY`` and
``check(index) -> List[Violation]`` (project-wide rules) or
``check_module(mod, index) -> List[Violation]`` (per-file rules). The
engine runs whichever is defined. Every rule encodes an invariant the
repo has already paid for violating — the docstring of each module names
the motivating PR.
"""

from __future__ import annotations

from typing import Dict, List

from . import (
    r1_gc_reentrancy,
    r2_blocking_in_async,
    r3_lock_across_await,
    r4_task_leak,
    r5_exception_pickle,
    r6_unbounded_rpc,
    r7_untracked_spawn,
    r8_config_knobs,
    r9_view_escape,
    r10_grow_only,
    r11_loop_stop_strands_client,
    r12_lock_order,
    r13_thread_affinity,
    r14_wire_contract,
)

ALL_RULES = [
    r1_gc_reentrancy,
    r2_blocking_in_async,
    r3_lock_across_await,
    r4_task_leak,
    r5_exception_pickle,
    r6_unbounded_rpc,
    r7_untracked_spawn,
    r8_config_knobs,
    r9_view_escape,
    r10_grow_only,
    r11_loop_stop_strands_client,
    r12_lock_order,
    r13_thread_affinity,
    r14_wire_contract,
]

RULES_BY_ID: Dict[str, object] = {m.RULE_ID: m for m in ALL_RULES}


def rule_catalog() -> List[Dict[str, str]]:
    return [{"id": m.RULE_ID, "summary": m.SUMMARY} for m in ALL_RULES]
