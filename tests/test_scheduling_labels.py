"""Node-label scheduling strategy test (reference:
python/ray/util/scheduling_strategies.py:135 NodeLabelSchedulingStrategy)."""

import ray_tpu
from ray_tpu.util.scheduling_strategies import NodeLabelSchedulingStrategy


def test_node_label_strategy(ray_label_cluster):
    @ray_tpu.remote
    def where():
        return ray_tpu.get_runtime_context().get_node_id()

    # Pin to the node labeled role=worker.
    node = ray_tpu.get(where.options(
        scheduling_strategy=NodeLabelSchedulingStrategy(
            hard={"role": ["worker"]}),
    ).remote())
    labeled = [n for n in ray_tpu.nodes()
               if n.get("labels", {}).get("role") == "worker"]
    assert len(labeled) == 1
    assert node == labeled[0]["node_id"]


def test_node_label_not_in(ray_label_cluster):
    from ray_tpu.util.scheduling_strategies import NotIn

    @ray_tpu.remote
    def where():
        return ray_tpu.get_runtime_context().get_node_id()

    node = ray_tpu.get(where.options(
        scheduling_strategy=NodeLabelSchedulingStrategy(
            hard={"role": NotIn("head")}),
    ).remote(), timeout=30)
    head = [n for n in ray_tpu.nodes()
            if n.get("labels", {}).get("role") == "head"][0]["node_id"]
    assert node != head


def test_label_constraint_ops():
    from ray_tpu._private.resources import (
        label_constraints_match, normalize_label_constraints)
    from ray_tpu.util.scheduling_strategies import (
        DoesNotExist, Exists, In, NotIn)

    wire = normalize_label_constraints({
        "a": In("x", "y"), "b": NotIn("z"), "c": Exists(),
        "d": DoesNotExist(), "e": "lit", "f": ["p", "q"]})
    assert label_constraints_match(
        {"a": "x", "b": "w", "c": "anything", "e": "lit", "f": "q"}, wire)
    assert not label_constraints_match({"a": "z"}, wire)          # a not in
    assert not label_constraints_match(
        {"a": "x", "b": "z", "c": "1", "e": "lit", "f": "q"}, wire)  # b NotIn
    assert not label_constraints_match(
        {"a": "x", "b": "w", "e": "lit", "f": "q"}, wire)         # c missing
    assert not label_constraints_match(
        {"a": "x", "c": "1", "d": "1", "e": "lit", "f": "q"}, wire)  # d present
