"""Durable workflows (reference: python/ray/workflow/ — workflow.run
api.py:123, run_async :177, WorkflowExecutor + step checkpointing
workflow_storage.py, continuations workflow_executor.py, event system
http_event_provider.py).

Executes a ``ray_tpu.dag`` graph with every step's result checkpointed to
storage; ``resume`` re-runs the graph, skipping steps whose checkpoints
exist — lineage-on-disk rather than lineage-in-memory.

Dynamic workflows: a step may return ``workflow.continuation(sub_dag)``;
the engine executes the sub-DAG as that step's continuation, each sub-step
durably checkpointed under the parent step's key prefix, so a crash inside
a continuation resumes mid-continuation (reference:
workflow_executor.py's continuation handling).

Storage is scheme-pluggable: ``init("mock://bucket/workflows")`` (or any
registered backend, _private/storage.py) persists checkpoints remotely —
the reference's equivalent of workflow storage on S3/GCS.
"""

from __future__ import annotations

import json
import os
import time
from typing import Any, Dict, List, Optional

import ray_tpu
from ray_tpu.dag import DAGNode, FunctionNode, InputNode, MultiOutputNode

_storage_root = os.path.expanduser("~/ray_tpu_workflows")


def init(storage: Optional[str] = None) -> None:
    global _storage_root
    if storage:
        _storage_root = storage
    _Store(_storage_root).makedirs("")


# --------------------------------------------------------------- storage
class _Store:
    """Workflow storage over a local dir OR a remote URI (scheme resolves
    a StorageBackend — reference: workflow_storage.py over pyarrow fs)."""

    def __init__(self, root: str):
        from ray_tpu._private.storage import is_remote_uri

        self.root = root
        self.remote = is_remote_uri(root)

    def _backend(self):
        from ray_tpu._private.storage import get_storage_backend

        return get_storage_backend(self.root)

    def _join(self, *parts: str) -> str:
        from ray_tpu._private.storage import join_uri

        if self.remote:
            return join_uri(self.root, *parts)
        return os.path.join(self.root, *parts)

    def makedirs(self, rel: str) -> None:
        if self.remote:
            return
        os.makedirs(self._join(rel) if rel else self.root, exist_ok=True)

    def write_bytes(self, rel: str, data: bytes) -> None:
        if self.remote:
            self._backend().write_bytes(self._join(rel), data)
            return
        path = self._join(rel)
        os.makedirs(os.path.dirname(path), exist_ok=True)
        tmp = path + ".tmp"
        with open(tmp, "wb") as f:
            f.write(data)
        os.replace(tmp, path)

    def read_bytes(self, rel: str) -> Optional[bytes]:
        try:
            if self.remote:
                return self._backend().read_bytes(self._join(rel))
            with open(self._join(rel), "rb") as f:
                return f.read()
        except (FileNotFoundError, NotADirectoryError):
            return None

    def exists(self, rel: str) -> bool:
        if self.remote:
            return self._backend().exists(self._join(rel))
        return os.path.exists(self._join(rel))

    def listdir(self, rel: str = "") -> List[str]:
        if self.remote:
            return self._backend().listdir(
                self._join(rel) if rel else self.root)
        p = self._join(rel) if rel else self.root
        return sorted(os.listdir(p)) if os.path.isdir(p) else []

    def delete(self, rel: str) -> None:
        if self.remote:
            self._backend().delete(self._join(rel))
            return
        import shutil

        shutil.rmtree(self._join(rel), ignore_errors=True)


# ---------------------------------------------------------- continuations
class Continuation:
    """Returned by a step to hand execution to a dynamically-built
    sub-DAG (reference: workflow.continuation — api.py)."""

    def __init__(self, dag: DAGNode):
        if not isinstance(dag, DAGNode):
            raise TypeError("continuation() takes a bound DAG node")
        self.dag = dag


def continuation(dag: DAGNode) -> Continuation:
    return Continuation(dag)


def _node_keys(root: DAGNode, prefix: str = "") -> Dict[int, str]:
    """Deterministic step keys: postorder index + function name."""
    keys: Dict[int, str] = {}
    counter = [0]

    def visit(node: DAGNode):
        if id(node) in keys:
            return
        for a in list(node._bound_args) + list(node._bound_kwargs.values()):
            if isinstance(a, DAGNode):
                visit(a)
        name = type(node).__name__
        if isinstance(node, FunctionNode):
            name = getattr(node._remote_fn, "__name__", "fn")
        keys[id(node)] = f"{prefix}step_{counter[0]:04d}_{name}"
        counter[0] += 1

    visit(root)
    return keys


class _DurableExecutor:
    def __init__(self, workflow_id: str, root: DAGNode, prefix: str = ""):
        self.workflow_id = workflow_id
        self.store = _Store(_storage_root)
        self.store.makedirs(workflow_id)
        self.keys = _node_keys(root, prefix)
        self.root = root

    def _ckpt_rel(self, node) -> str:
        return f"{self.workflow_id}/{self.keys[id(node)]}.pkl"

    def _set_status(self, status: str) -> None:
        self.store.write_bytes(
            f"{self.workflow_id}/status.json",
            json.dumps({"status": status, "time": time.time()}).encode())

    def run(self, *input_args, **input_kwargs) -> Any:
        self._set_status("RUNNING")
        try:
            result = self.run_inner(input_args, input_kwargs)
            self._set_status("SUCCESSFUL")
            return result
        except Exception:
            self._set_status("FAILED")
            raise

    def run_inner(self, input_args, input_kwargs) -> Any:
        result = self._exec(self.root, input_args, input_kwargs)
        if isinstance(result, ray_tpu.ObjectRef):
            result = ray_tpu.get(result)
        elif isinstance(result, list):
            result = [ray_tpu.get(r) if isinstance(r, ray_tpu.ObjectRef)
                      else r for r in result]
        return result

    def _exec(self, node: DAGNode, input_args, input_kwargs):
        if isinstance(node, InputNode):
            return node._execute_node({}, input_args, input_kwargs)
        if isinstance(node, MultiOutputNode):
            return [self._exec(a, input_args, input_kwargs)
                    for a in node._bound_args]
        from ray_tpu._private import serialization as ser

        rel = self._ckpt_rel(node)
        data = self.store.read_bytes(rel)
        if data is not None:
            value = ser.loads(data)
        else:
            def resolve(a):
                if isinstance(a, DAGNode):
                    return self._exec(a, input_args, input_kwargs)
                return a

            args = [resolve(a) for a in node._bound_args]
            kwargs = {k: resolve(v) for k, v in node._bound_kwargs.items()}
            if isinstance(node, FunctionNode):
                ref = node._remote_fn.remote(*args, **kwargs)
            else:
                method = getattr(node._actor, node._method_name)
                ref = method.remote(*args, **kwargs)
            value = ray_tpu.get(ref)
            # COMMIT the raw step result now — even (especially) when it
            # is a Continuation: the dynamic sub-DAG it names is then
            # durable, and a crash inside the continuation resumes from
            # the sub-steps' own checkpoints instead of re-running this
            # step (reference: workflow_executor.py persists the
            # continuation DAG before descending)
            self.store.write_bytes(rel, ser.dumps(value))
        # dynamic workflow: run the continuation chain, each level's steps
        # checkpointing under this step's key prefix
        depth = 0
        while isinstance(value, Continuation):
            sub = _DurableExecutor(
                self.workflow_id, value.dag,
                prefix=f"{self.keys[id(node)]}.c{depth}.")
            value = sub.run_inner(input_args, input_kwargs)
            depth += 1
        return value


def run(dag: DAGNode, *, workflow_id: Optional[str] = None,
        args: tuple = (), kwargs: Optional[Dict] = None) -> Any:
    """Execute durably; every completed step is checkpointed."""
    init()
    workflow_id = workflow_id or f"workflow_{int(time.time() * 1000)}"
    return _DurableExecutor(workflow_id, dag).run(
        *args, **(kwargs or {}))


def run_async(dag: DAGNode, *, workflow_id: Optional[str] = None,
              args: tuple = (), kwargs: Optional[Dict] = None):
    """Non-blocking run (reference: workflow/api.py:177 run_async) —
    returns a concurrent.futures.Future of the workflow result."""
    from concurrent.futures import ThreadPoolExecutor

    init()
    workflow_id = workflow_id or f"workflow_{int(time.time() * 1000)}"
    pool = ThreadPoolExecutor(max_workers=1,
                              thread_name_prefix=f"wf-{workflow_id}")
    fut = pool.submit(
        lambda: _DurableExecutor(workflow_id, dag).run(
            *args, **(kwargs or {})))
    fut.add_done_callback(lambda _: pool.shutdown(wait=False))
    fut.workflow_id = workflow_id
    return fut


# ------------------------------------------------------------------ events
class EventListener:
    """Event source ABC (reference: workflow/event_system —
    EventListener.poll_for_event). ``poll_for_event`` blocks until the
    event arrives and returns its payload."""

    def poll_for_event(self) -> Any:
        raise NotImplementedError


class TimerListener(EventListener):
    """Fires after ``seconds`` (reference: the timer event example)."""

    def __init__(self, seconds: float):
        self.seconds = seconds

    def poll_for_event(self) -> float:
        time.sleep(self.seconds)
        return time.time()


class FileEventListener(EventListener):
    """Fires when ``path`` exists; payload is its contents (a minimal
    external-event provider usable across processes)."""

    def __init__(self, path: str, poll_interval: float = 0.1):
        self.path = path
        self.poll_interval = poll_interval

    def poll_for_event(self) -> bytes:
        while not os.path.exists(self.path):
            time.sleep(self.poll_interval)
        with open(self.path, "rb") as f:
            return f.read()


class HTTPEventProvider(EventListener):
    """Durable HTTP event delivery (reference:
    python/ray/workflow/http_event_provider.py — an HTTP endpoint
    receives ``POST /event/<key>`` and the payload is COMMITTED to
    workflow storage before the sender gets 200, so a delivered event
    survives a crash before the workflow consumes it).

    ``poll_for_event`` first checks the durable spool (resume path), then
    serves one HTTP request. The bound endpoint is written to
    ``<storage>/_events/<key>.addr`` as ``host:port`` (and the legacy
    ``.port`` file) so external senders can discover it. The default bind
    is loopback (the endpoint is unauthenticated); multi-host deployments
    with shared storage must opt in with ``bind_host="0.0.0.0"``, which
    advertises the node's outbound IP in the ``.addr`` file.
    """

    def __init__(self, event_key: str, port: int = 0,
                 timeout_s: float = 300.0, bind_host: str = "127.0.0.1"):
        self.event_key = event_key
        self.port = port
        self.timeout_s = timeout_s
        self.bind_host = bind_host

    def _spool_rel(self) -> str:
        return f"_events/{self.event_key}.payload"

    def poll_for_event(self) -> bytes:
        init()
        store = _Store(_storage_root)
        spooled = store.read_bytes(self._spool_rel())
        if spooled is not None:  # durably delivered earlier (resume path)
            return spooled

        from http.server import BaseHTTPRequestHandler, HTTPServer

        received: List[bytes] = []
        key = self.event_key

        class Handler(BaseHTTPRequestHandler):
            def do_POST(self):  # noqa: N802 (http.server API)
                if self.path.rstrip("/").split("/")[-1] != key:
                    self.send_response(404)
                    self.end_headers()
                    return
                n = int(self.headers.get("Content-Length", 0))
                payload = self.rfile.read(n)
                # COMMIT before acking: that is the durability contract
                store.write_bytes(f"_events/{key}.payload", payload)
                received.append(payload)
                self.send_response(200)
                self.end_headers()
                self.wfile.write(b"{}")

            def log_message(self, *a):  # quiet
                pass

        server = HTTPServer((self.bind_host, self.port), Handler)
        server.timeout = 1.0
        bound_port = server.server_address[1]
        from ray_tpu._private.worker import node_ip

        host = node_ip() if self.bind_host in ("0.0.0.0", "") \
            else self.bind_host
        store.write_bytes(f"_events/{key}.addr",
                          f"{host}:{bound_port}".encode())
        store.write_bytes(f"_events/{key}.port", str(bound_port).encode())
        deadline = time.monotonic() + self.timeout_s
        try:
            while not received and time.monotonic() < deadline:
                server.handle_request()
        finally:
            server.server_close()
        if not received:
            raise TimeoutError(
                f"no event delivered for key {key!r} "
                f"within {self.timeout_s}s")
        return received[0]


def wait_for_event(listener_cls, *args, **kwargs) -> DAGNode:
    """A DAG step that completes when the listener's event arrives
    (reference: workflow.wait_for_event). Like any step, the received
    payload is checkpointed — a resumed workflow does NOT wait again."""
    import ray_tpu

    # the step executes in a WORKER process whose workflow module starts
    # at the default storage root; carry the driver's configured root so
    # storage-backed listeners (HTTPEventProvider spool/port files) land
    # where the driver and external senders look
    configured_root = _storage_root

    @ray_tpu.remote
    def __wait_for_event__():
        from ray_tpu import workflow as _wf

        _wf.init(configured_root)
        return listener_cls(*args, **kwargs).poll_for_event()

    return __wait_for_event__.bind()


def resume(workflow_id: str, dag: DAGNode, *, args: tuple = (),
           kwargs: Optional[Dict] = None) -> Any:
    """Re-run a workflow; completed steps are served from checkpoints.

    (The reference serializes the DAG into storage so resume needs no code;
    here the caller re-supplies the graph and storage supplies the state.)
    """
    init()
    store = _Store(_storage_root)
    if not (store.exists(f"{workflow_id}/status.json")
            or store.listdir(workflow_id)):
        raise ValueError(f"no workflow {workflow_id!r}")
    return _DurableExecutor(workflow_id, dag).run(*args, **(kwargs or {}))


def get_status(workflow_id: str) -> Optional[str]:
    data = _Store(_storage_root).read_bytes(f"{workflow_id}/status.json")
    if data is None:
        return None
    return json.loads(data)["status"]


def list_all() -> List[Dict]:
    init()
    out = []
    for wid in _Store(_storage_root).listdir():
        if wid.startswith("_"):
            continue
        status = get_status(wid)
        if status:
            out.append({"workflow_id": wid, "status": status})
    return out


def delete(workflow_id: str) -> None:
    _Store(_storage_root).delete(workflow_id)


# ---------------------------------------------------------- virtual actors
class VirtualActorHandle:
    """Durable actor: state lives in workflow storage, every method call
    runs as a checkpointed step (reference: workflow's virtual-actor
    durable state — methods load state, execute in a task, commit the new
    state before returning)."""

    def __init__(self, cls, actor_id: str, init_args, init_kwargs,
                 storage_root: str):
        self._cls = cls
        self._actor_id = actor_id
        self._init = (init_args, init_kwargs)
        self._root = storage_root

    def _state_rel(self) -> str:
        return f"_va/{self._actor_id}/state.pkl"

    def _load_state(self):
        from ray_tpu._private import serialization as ser

        store = _Store(self._root)
        data = store.read_bytes(self._state_rel())
        if data is not None:
            return ser.loads(data)
        inst = self._cls(*self._init[0], **self._init[1])
        return inst.__dict__

    def _commit_state(self, state: dict) -> None:
        from ray_tpu._private import serialization as ser

        _Store(self._root).write_bytes(self._state_rel(), ser.dumps(state))

    def __getattr__(self, name: str):
        if name.startswith("_"):
            raise AttributeError(name)
        method = getattr(self._cls, name)

        class _Method:
            def run(me, *args, **kwargs):
                import ray_tpu

                cls, init, root = self._cls, self._init, self._root
                rel = self._state_rel()

                @ray_tpu.remote
                def __virtual_actor_step__(state_dict):
                    inst = cls.__new__(cls)
                    inst.__dict__.update(state_dict)
                    result = method(inst, *args, **kwargs)
                    return result, inst.__dict__

                state = self._load_state()
                result, new_state = ray_tpu.get(
                    __virtual_actor_step__.remote(state))
                # commit AFTER execution: a crash mid-step replays the
                # method against the old state (at-least-once, like
                # workflow steps before their checkpoint lands)
                self._commit_state(new_state)
                return result

            def run_async(me, *args, **kwargs):
                from concurrent.futures import ThreadPoolExecutor

                pool = ThreadPoolExecutor(max_workers=1)
                fut = pool.submit(me.run, *args, **kwargs)
                fut.add_done_callback(lambda _: pool.shutdown(wait=False))
                return fut

        return _Method()

    def state(self) -> dict:
        """Current committed state (for inspection/tests)."""
        return dict(self._load_state())


class VirtualActorClass:
    def __init__(self, cls):
        self._cls = cls

    def get_or_create(self, actor_id: str, *args, **kwargs
                      ) -> VirtualActorHandle:
        init()
        return VirtualActorHandle(self._cls, actor_id, args, kwargs,
                                  _storage_root)


def virtual_actor(cls) -> VirtualActorClass:
    """Durable-actor decorator (reference: workflow virtual actors)."""
    return VirtualActorClass(cls)
