"""Node memory monitor + OOM worker-killing policy (reference:
``src/ray/common/memory_monitor.h:52`` MemoryMonitor and
``src/ray/raylet/worker_killing_policy.h:34`` — group-by-owner and
retriable-FIFO victim selection).

Runs in the node agent's event loop: when host memory crosses the usage
threshold, pick a leased worker to kill — preferring (1) retriable tasks,
(2) the owner with the most running tasks (group-by-owner: keeps at least
one task per owner making progress), (3) youngest lease first (FIFO by
lease age protects long-running work). The killed task fails with an
``OutOfMemoryError`` the owner can retry.
"""

from __future__ import annotations

import time
from typing import Callable, Dict, List, Optional, Tuple


class MemoryMonitor:
    def __init__(self, usage_threshold: float = 0.95,
                 min_memory_free_bytes: Optional[int] = None):
        self.usage_threshold = usage_threshold
        self.min_memory_free_bytes = min_memory_free_bytes

    def get_memory_usage(self) -> Tuple[int, int]:
        """(used, total) bytes; cgroup-aware when limits apply."""
        import psutil

        vm = psutil.virtual_memory()
        used, total = vm.total - vm.available, vm.total
        try:  # container limit, if tighter (reference reads cgroup files)
            with open("/sys/fs/cgroup/memory.max") as f:
                raw = f.read().strip()
            if raw != "max":
                limit = int(raw)
                if limit < total:
                    with open("/sys/fs/cgroup/memory.current") as f:
                        used = int(f.read().strip())
                    total = limit
        except OSError:
            pass
        return used, total

    def is_pressure(self) -> bool:
        used, total = self.get_memory_usage()
        if self.min_memory_free_bytes is not None:
            return total - used < self.min_memory_free_bytes
        return used / max(total, 1) > self.usage_threshold


def pick_oom_victim(leases: List[Dict]) -> Optional[Dict]:
    """Choose which leased worker to kill under memory pressure.

    ``leases``: [{"lease": id, "retriable": bool, "owner": str,
                  "start": monotonic, ...}]
    Policy (reference: worker_killing_policy_group_by_owner.h +
    ...retriable_fifo.h): retriable before non-retriable; within a class,
    the owner with the most running tasks loses its YOUNGEST task, so every
    owner keeps its oldest task running.
    """
    if not leases:
        return None
    by_owner: Dict[str, int] = {}
    for entry in leases:
        by_owner[entry.get("owner") or ""] = \
            by_owner.get(entry.get("owner") or "", 0) + 1

    def sort_key(entry):
        return (
            0 if entry.get("retriable", True) else 1,
            -by_owner[entry.get("owner") or ""],
            -entry.get("start", 0.0),  # youngest first
        )

    return sorted(leases, key=sort_key)[0]


class OomKiller:
    """Periodic pressure check + kill loop hosted by the node agent."""

    def __init__(self, monitor: MemoryMonitor,
                 list_leases: Callable[[], List[Dict]],
                 kill: Callable[[Dict], None],
                 check_period_s: float = 1.0,
                 cooldown_s: float = 5.0):
        self.monitor = monitor
        self._list_leases = list_leases
        self._kill = kill
        self.check_period_s = check_period_s
        self.cooldown_s = cooldown_s
        self._last_kill = 0.0
        self.num_kills = 0

    async def run(self) -> None:
        import asyncio
        import logging

        warned = False
        while True:
            await asyncio.sleep(self.check_period_s)
            try:
                self.step()
            except Exception as e:
                if not warned:  # once: a broken monitor must not be silent
                    logging.getLogger("ray_tpu").error(
                        "memory monitor failing (%s); OOM protection is "
                        "NOT active on this node", e)
                    warned = True

    def step(self) -> bool:
        if time.monotonic() - self._last_kill < self.cooldown_s:
            return False
        if not self.monitor.is_pressure():
            return False
        victim = pick_oom_victim(self._list_leases())
        if victim is None:
            return False
        self._kill(victim)
        self._last_kill = time.monotonic()
        self.num_kills += 1
        return True
