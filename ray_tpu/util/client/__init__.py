"""``ray://`` client mode (reference: python/ray/util/client/ — gRPC proxy
driver described in python/ray/util/client/ARCHITECTURE.md: a thin client
ships pickled functions/args to a server that runs a real driver and holds
the object refs).

Here the transport is the framework's own length-prefixed RPC protocol
(_private/protocol.py) instead of gRPC: ``ClientServer`` embeds a real
driver, ``ClientContext`` (returned by ``ray_tpu.init("ray://host:port")``)
proxies remote()/get()/put()/actors to it. Refs on the client are
``ClientObjectRef`` handles naming server-held refs; the server releases
them when the client connection drops.
"""

from ray_tpu.util.client.client import ClientContext, ClientObjectRef, connect
from ray_tpu.util.client.server import ClientServer, serve

__all__ = ["ClientContext", "ClientObjectRef", "connect", "ClientServer",
           "serve"]
