from ray_tpu._private.accelerators.accelerator import AcceleratorManager
from ray_tpu._private.accelerators.tpu import TPUAcceleratorManager
from ray_tpu._private.accelerators.nvidia_gpu import NvidiaGPUAcceleratorManager
from ray_tpu._private.accelerators.other import (
    AMDGPUAcceleratorManager,
    HPUAcceleratorManager,
    IntelGPUAcceleratorManager,
    NeuronAcceleratorManager,
    NPUAcceleratorManager,
)


def get_all_accelerator_managers():
    return {
        "TPU": TPUAcceleratorManager,
        "GPU": NvidiaGPUAcceleratorManager,
        "neuron_cores": NeuronAcceleratorManager,
        "HPU": HPUAcceleratorManager,
        "NPU": NPUAcceleratorManager,
    }


def get_accelerator_manager(resource_name: str):
    return get_all_accelerator_managers().get(resource_name)
