"""Elastic training plane under chaos (ISSUE 20 tentpole coverage).

A pure-numpy deterministic SGD loop reports in-store sharded checkpoints;
``util.chaos.DaemonKiller`` SIGKILLs one train worker mid-epoch. The
recovery loop must surface the death as a typed restart (not a hang, not
a user-facing error), resume from the newest in-store checkpoint without
touching disk, and converge to a final state BYTE-equivalent to an
uninterrupted run. The numpy-only loop doubles as the "jax stays
unimported in workers" probe: nothing on the worker-side report/restore
path may drag the jax runtime in.
"""

import os
import pickle
import threading
import time

import pytest

import ray_tpu
from ray_tpu import train
from ray_tpu.train import (
    FailureConfig, InStoreCheckpoint, JaxTrainer, RunConfig, ScalingConfig)
from ray_tpu.util.chaos import DaemonKiller


@pytest.fixture(scope="module")
def ray4():
    if not ray_tpu.is_initialized():
        ray_tpu.init(num_cpus=4)
    yield
    ray_tpu.shutdown()


def _sgd_loop(config):
    """Deterministic full-batch SGD; every worker holds the replicated
    problem so any surviving subset continues the identical trajectory."""
    import hashlib
    import sys

    import numpy as np

    ctx = train.get_context()
    rank = ctx.get_world_rank()

    rng = np.random.RandomState(0)
    X = rng.randn(32, 4)
    w_true = rng.randn(4)
    y = X @ w_true

    start = 0
    in_store_restore = False
    w = np.zeros(4)
    ckpt = train.get_checkpoint()
    if ckpt is not None:
        in_store_restore = isinstance(ckpt, InStoreCheckpoint)
        state = pickle.loads(bytes(ckpt.get_file("state.pkl"))) \
            if in_store_restore else None
        if state is not None:
            start = state["step"] + 1
            w = state["w"]

    pid_file = config.get("pid_file")
    slow_gate = config.get("slow_gate")
    for step in range(start, config["steps"]):
        grad = 2.0 * X.T @ (X @ w - y) / len(y)
        w = w - 0.05 * grad
        loss = float(np.mean((X @ w - y) ** 2))
        if pid_file and rank == 1 and step >= 5 and \
                not os.path.exists(pid_file):
            with open(pid_file + ".tmp", "w") as f:
                f.write(str(os.getpid()))
            os.replace(pid_file + ".tmp", pid_file)
        if slow_gate and not os.path.exists(slow_gate):
            time.sleep(0.05)
        train.report(
            {"step": step, "loss": loss,
             "w_digest": hashlib.sha256(w.tobytes()).hexdigest(),
             "resumed_from": start,
             "in_store_restore": in_store_restore,
             "world_size": ctx.get_world_size(),
             "jax_loaded": "jax" in sys.modules},
            checkpoint=InStoreCheckpoint.from_state(
                {"state.pkl": pickle.dumps({"step": step, "w": w})},
                step=step))


def _fit(tmp_path, name, steps=40, num_workers=2, min_workers=None,
         pid_file=None, slow_gate=None, max_failures=3):
    trainer = JaxTrainer(
        _sgd_loop,
        train_loop_config={"steps": steps, "pid_file": pid_file,
                           "slow_gate": slow_gate},
        scaling_config=ScalingConfig(num_workers=num_workers,
                                     min_workers=min_workers,
                                     resources_per_worker={"CPU": 1}),
        run_config=RunConfig(
            name=name, storage_path=str(tmp_path),
            failure_config=FailureConfig(max_failures=max_failures)),
    )
    return trainer.fit()


def _restarts_metric_total() -> float:
    from ray_tpu.util import metrics

    m = metrics._REGISTRY.get("ray_tpu_train_restarts_total")
    if m is None:
        return 0.0
    return float(sum(v for _, v in m.snapshot().get("values", [])))


def _run_with_killer(tmp_path, name, **kw):
    """fit() with a DaemonKiller SIGKILLing the worker whose pid the
    rank-1 loop published — kill -9 mid-epoch, exactly once."""
    pid_file = str(tmp_path / f"{name}_victim_pid")
    slow_gate = str(tmp_path / f"{name}_go_fast")

    def victim(rec):
        try:
            with open(pid_file) as f:
                return rec["pid"] == int(f.read())
        except (OSError, ValueError):
            return False

    from ray_tpu._private.worker import global_worker

    killer = DaemonKiller(global_worker.session_dir, roles=("worker",),
                          interval_s=0.1, max_kills=1, filter_fn=victim)
    killer.run()

    def open_gate():
        while not killer.kills:
            time.sleep(0.1)
        open(slow_gate, "w").close()  # kill landed: sprint to the end

    gate = threading.Thread(target=open_gate, daemon=True)
    gate.start()
    try:
        result = _fit(tmp_path, name, pid_file=pid_file,
                      slow_gate=slow_gate, **kw)
    finally:
        killer.stop()
    gate.join(timeout=10)
    assert killer.kills, "the chaos kill never fired"
    return result


def test_worker_kill_resumes_byte_equivalent(ray4, tmp_path):
    clean = _fit(tmp_path, "clean",
                 slow_gate=str(tmp_path / "clean_go_fast"))
    open(str(tmp_path / "clean_go_fast"), "w").close()
    assert clean.error is None and clean.restarts == 0

    before = _restarts_metric_total()
    result = _run_with_killer(tmp_path, "chaos")

    # typed recovery, not a wedge and not a user-facing failure
    assert result.error is None, result.error
    assert result.restarts >= 1
    assert _restarts_metric_total() > before

    m = result.metrics
    assert m["step"] == 39
    # the restarted incarnation resumed from the in-store checkpoint,
    # not from scratch and not from a disk materialization
    assert m["resumed_from"] >= 1
    assert m["in_store_restore"] is True
    # byte-equivalent trajectory across the crash boundary
    assert m["w_digest"] == clean.metrics["w_digest"]
    assert m["loss"] == clean.metrics["loss"]
    # the numpy-only train path must not have dragged jax into workers
    assert m["jax_loaded"] is False
    assert clean.metrics["jax_loaded"] is False


def test_worker_kill_elastic_shrinks_world(ray4, tmp_path):
    """With elastic bounds, a death restarts at the surviving world size
    instead of re-demanding the dead worker's slot."""
    result = _run_with_killer(tmp_path, "elastic", min_workers=1)
    assert result.error is None, result.error
    assert result.restarts >= 1
    m = result.metrics
    assert m["step"] == 39
    assert m["world_size"] == 1  # shrank from 2 to the survivor
    assert m["resumed_from"] >= 1
    assert m["in_store_restore"] is True


def test_user_error_is_not_retried_forever(ray4, tmp_path):
    """A deterministic user-loop raise must burn through max_failures and
    surface, never loop forever (restart policy must distinguish
    train_fn_error from worker death)."""

    def bad_loop(config):
        raise RuntimeError("always fails")

    trainer = JaxTrainer(
        bad_loop,
        scaling_config=ScalingConfig(num_workers=1),
        run_config=RunConfig(
            name="bad", storage_path=str(tmp_path),
            failure_config=FailureConfig(max_failures=1)),
    )
    result = trainer.fit()
    assert result.error is not None
    assert "always fails" in str(result.error)
