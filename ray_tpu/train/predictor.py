"""Predictors + batch inference (reference:
python/ray/train/predictor.py Predictor ABC and
python/ray/train/batch_predictor.py BatchPredictor — load a checkpoint
once per worker, map it over a Dataset with an actor pool).

TPU-first deviations: the flagship predictor is ``JaxPredictor`` (a
jitted apply over host numpy batches, bf16-friendly), and the actor-pool
map rides ``Dataset.map_batches`` with class constructors so each
replica materializes the checkpoint exactly once — on TPU nodes that is
one HBM upload per replica, not per batch.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional, Type

import numpy as np

from ray_tpu.train._checkpoint import Checkpoint


class Predictor:
    """One loaded model; predicts on column-batches (dict of numpy)."""

    @classmethod
    def from_checkpoint(cls, checkpoint: Checkpoint, **kwargs) -> "Predictor":
        raise NotImplementedError

    def predict(self, batch: Dict[str, np.ndarray]) -> Dict[str, np.ndarray]:
        raise NotImplementedError


class JaxPredictor(Predictor):
    """Predictor over a pure ``apply(params, batch_array) -> array`` fn.

    The checkpoint must hold {"params": pytree}; ``apply`` is passed by
    the caller (models are code, checkpoints are data — the reference's
    framework predictors rebuild the model the same way). The apply is
    jitted once; batches arrive as the dataset's numpy columns and
    predictions come back as host numpy under ``output_column``.
    """

    def __init__(self, params: Any, apply_fn: Callable,
                 feature_column: str = "features",
                 output_column: str = "predictions"):
        import jax

        self.params = params
        self.feature_column = feature_column
        self.output_column = output_column
        self._apply = jax.jit(apply_fn)

    @classmethod
    def from_checkpoint(cls, checkpoint: Checkpoint, *,
                        apply_fn: Callable,
                        feature_column: str = "features",
                        output_column: str = "predictions"
                        ) -> "JaxPredictor":
        state = checkpoint.to_dict()
        params = state.get("params", state)
        return cls(params, apply_fn, feature_column=feature_column,
                   output_column=output_column)

    def predict(self, batch: Dict[str, np.ndarray]) -> Dict[str, np.ndarray]:
        features = batch[self.feature_column]
        out = np.asarray(self._apply(self.params, features))
        result = dict(batch)
        result[self.output_column] = out
        return result


class TorchPredictor(Predictor):
    """torch.nn.Module inference (reference: train/torch/torch_predictor.py);
    the checkpoint holds {"model_state": state_dict} and the caller
    supplies the module factory."""

    def __init__(self, model, feature_column: str = "features",
                 output_column: str = "predictions"):
        import torch

        self.model = model.eval()
        self.feature_column = feature_column
        self.output_column = output_column
        self._torch = torch

    @classmethod
    def from_checkpoint(cls, checkpoint: Checkpoint, *,
                        model_factory: Callable,
                        feature_column: str = "features",
                        output_column: str = "predictions"
                        ) -> "TorchPredictor":
        import torch

        model = model_factory()
        state = checkpoint.to_dict()
        if "model_state" in state:
            model.load_state_dict(state["model_state"])
        return cls(model, feature_column=feature_column,
                   output_column=output_column)

    def predict(self, batch: Dict[str, np.ndarray]) -> Dict[str, np.ndarray]:
        torch = self._torch
        with torch.no_grad():
            x = torch.as_tensor(np.asarray(batch[self.feature_column]))
            out = self.model(x).numpy()
        result = dict(batch)
        result[self.output_column] = out
        return result


class _PredictorCallable:
    """Actor-pool callable for map_batches: builds the predictor ONCE in
    the replica's constructor from the shipped checkpoint. Dict-backed
    checkpoints travel by value (cluster-safe); directory checkpoints
    travel by path (shared-filesystem deployments, the reference's
    storage-path model)."""

    def __init__(self, predictor_cls, shipped, from_checkpoint_kwargs: Dict):
        kind, payload = shipped
        ckpt = (Checkpoint.from_dict(payload) if kind == "dict"
                else Checkpoint.from_directory(payload))
        self.predictor = predictor_cls.from_checkpoint(
            ckpt, **from_checkpoint_kwargs)

    def __call__(self, batch):
        return self.predictor.predict(batch)


def _ship_checkpoint(checkpoint: Checkpoint):
    import os

    if os.path.exists(os.path.join(checkpoint.path, "_dict.pkl")):
        return ("dict", checkpoint.to_dict())
    return ("path", checkpoint.path)


class BatchPredictor:
    """Checkpoint + predictor class → scalable Dataset inference
    (reference: train/batch_predictor.py:40)."""

    def __init__(self, checkpoint: Checkpoint,
                 predictor_cls: Type[Predictor], **from_checkpoint_kwargs):
        self.checkpoint = checkpoint
        self.predictor_cls = predictor_cls
        self.from_checkpoint_kwargs = from_checkpoint_kwargs

    @classmethod
    def from_checkpoint(cls, checkpoint: Checkpoint,
                        predictor_cls: Type[Predictor],
                        **kwargs) -> "BatchPredictor":
        return cls(checkpoint, predictor_cls, **kwargs)

    def predict(self, dataset, *, batch_size: int = 256,
                concurrency: int = 2, num_cpus: Optional[float] = None,
                num_tpus: Optional[float] = None):
        """Lazy: returns the mapped Dataset; iterate/materialize to run."""
        return dataset.map_batches(
            _PredictorCallable,
            batch_size=batch_size,
            fn_constructor_args=(self.predictor_cls,
                                 _ship_checkpoint(self.checkpoint),
                                 self.from_checkpoint_kwargs),
            concurrency=concurrency,
            num_cpus=num_cpus,
            num_tpus=num_tpus,
        )
