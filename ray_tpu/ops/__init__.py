"""ray_tpu.ops — TPU compute kernels (Pallas) and their reference fallbacks.

The hot ops of the model families live here: flash attention (Pallas, VMEM
blocked, online softmax), ring attention (seq-parallel via ppermute), and
fused pieces XLA doesn't get right on its own. Everything has a pure-XLA
reference path so the suite runs on the CPU test mesh.
"""

from ray_tpu.ops.attention import attention, reference_attention

__all__ = ["attention", "reference_attention"]
