"""Project-wide symbol index, call graph, and fixpoint reachability.

The runtime's worst shipped bugs were *reachability* properties, not
single-statement ones: the MemoryStore deadlock was ``ObjectRef.__del__``
→ ``ReferenceCounter.remove_local_ref`` → ``MemoryStore.delete`` → plain
``Lock`` — three modules apart. Rules that need "can GC context reach
this lock?" get it from here: a conservative, name-based call graph with
an ambiguity cutoff, walked to fixpoint.

Resolution strategy (deliberately approximate — Python has no static
types here):

- ``name(...)``          → same-module function, else a project function
                           imported by that name.
- ``self.m(...)``        → method ``m`` on the enclosing class, else on a
                           project base class of it, else global-by-name.
- ``obj.m(...)``         → every project function/method named ``m``,
                           but only if the name has at most
                           ``AMBIGUITY_CUTOFF`` definitions project-wide.
                           Ubiquitous names (``get``, ``put``, ``call``)
                           exceed the cutoff and contribute no edge —
                           that keeps reachability from exploding to the
                           whole tree while still following distinctive
                           hops like ``remove_local_ref``.

Lock identity: every ``self.X = threading.Lock()/RLock()`` (and
module-level ``X = Lock()``) assignment in the project is indexed, so a
``with self._lock:`` inside a method resolves to the lock *kind* declared
by its class.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Set, Tuple

from .model import ModuleInfo

AMBIGUITY_CUTOFF = 4

# Attribute names that are stdlib-protocol vocabulary (lock/future/queue/
# event methods). Calling `obj.acquire()` on an *unknown* receiver is
# almost always a synchronization primitive, not a project method — a
# global-by-name edge through these would wire every __del__ to every
# class that happens to define `set` or `release` and drown R1 in false
# chains. `self.m(...)` still resolves through these names normally (the
# receiver's class is known).
GLOBAL_RESOLVE_BLOCKLIST = {
    "acquire", "release", "locked", "wait", "notify", "notify_all",
    "set", "clear", "is_set", "set_result", "set_exception", "result",
    "exception", "done", "cancel", "cancelled", "add_done_callback",
    "get", "put", "get_nowait", "put_nowait", "close", "join", "start",
    "run", "stop", "send", "recv", "read", "write", "flush", "append",
    "pop", "update", "items", "keys", "values", "copy", "encode",
    "decode", "format",
    # more stdlib vocabulary that manufactured cross-module edges once
    # R12 started chaining EA sets through them: StreamWriter.drain,
    # str.partition, list.count, json.dump/load, socket.connect
    "drain", "partition", "count", "dump", "dumps", "load", "loads",
    "connect", "index", "insert", "extend", "sort", "split", "strip",
    "seek", "submit", "shutdown",
}

_LOCK_FACTORIES = {"Lock": "Lock", "RLock": "RLock"}


@dataclass
class FunctionInfo:
    name: str
    qualname: str            # "Class.meth" or "func"
    module: ModuleInfo
    node: ast.AST            # FunctionDef | AsyncFunctionDef | Lambda
    class_name: Optional[str] = None

    @property
    def ref(self) -> str:
        return f"{self.module.relpath}::{self.qualname}"


@dataclass
class LockSite:
    node: ast.AST            # the With item / acquire() call
    kind: str                # "Lock" | "RLock" | "unknown"
    name: str                # "self._lock", "_GLOBAL_LOCK", ...
    fn: FunctionInfo


@dataclass
class ClassInfo:
    name: str
    module: ModuleInfo
    node: ast.ClassDef
    bases: List[str] = field(default_factory=list)
    methods: Dict[str, FunctionInfo] = field(default_factory=dict)
    lock_attrs: Dict[str, str] = field(default_factory=dict)  # attr -> kind


def _call_name(func: ast.AST) -> Tuple[Optional[str], Optional[str]]:
    """(base, attr) for a call target: ('time','sleep'), (None,'foo'),
    ('self','meth'), ('<expr>','meth') for computed bases."""
    if isinstance(func, ast.Name):
        return None, func.id
    if isinstance(func, ast.Attribute):
        v = func.value
        if isinstance(v, ast.Name):
            return v.id, func.attr
        return "<expr>", func.attr
    return None, None


def _is_lock_ctor(call: ast.Call) -> Optional[str]:
    """'Lock'/'RLock' when ``call`` constructs a threading lock."""
    base, attr = _call_name(call.func)
    if attr not in _LOCK_FACTORIES:
        return None
    if base in (None, "threading", "_threading", "th"):
        return _LOCK_FACTORIES[attr]
    return None


class ProjectIndex:
    """Symbol tables over every analyzed module, built once per run."""

    def __init__(self, modules: Iterable[ModuleInfo]):
        self.modules: List[ModuleInfo] = list(modules)
        self.classes: Dict[str, List[ClassInfo]] = {}
        self.module_functions: Dict[Tuple[str, str], FunctionInfo] = {}
        self.by_method_name: Dict[str, List[FunctionInfo]] = {}
        self.module_locks: Dict[Tuple[str, str], str] = {}
        # name imported in module -> source function name (only same-name
        # from-imports matter for call resolution)
        self.weakref_callbacks: List[Tuple[ast.AST, ModuleInfo]] = []
        for mod in self.modules:
            self._index_module(mod)

    # ------------------------------------------------------------ build
    def _index_module(self, mod: ModuleInfo) -> None:
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.ClassDef):
                self._index_class(mod, node)
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                if not any(isinstance(a, ast.ClassDef)
                           for a in mod.ancestors(node)):
                    fi = FunctionInfo(node.name, mod.qualname(node), mod,
                                      node)
                    self.module_functions[(mod.relpath, node.name)] = fi
                    self.by_method_name.setdefault(node.name, []).append(fi)
            elif isinstance(node, ast.Assign):
                # module-level LOCK = threading.Lock()
                if isinstance(node.value, ast.Call):
                    kind = _is_lock_ctor(node.value)
                    if kind:
                        for tgt in node.targets:
                            if isinstance(tgt, ast.Name) and not any(
                                    isinstance(a, (ast.FunctionDef,
                                                   ast.AsyncFunctionDef,
                                                   ast.ClassDef))
                                    for a in mod.ancestors(node)):
                                self.module_locks[(mod.relpath, tgt.id)] = kind
            elif isinstance(node, ast.Call):
                self._maybe_weakref_callback(mod, node)

    def _index_class(self, mod: ModuleInfo, node: ast.ClassDef) -> None:
        ci = ClassInfo(node.name, mod, node)
        for b in node.bases:
            base, attr = _call_name(b) if isinstance(b, ast.Call) else (
                (b.value.id, b.attr) if isinstance(b, ast.Attribute)
                and isinstance(b.value, ast.Name)
                else (None, b.id) if isinstance(b, ast.Name) else (None, None))
            if attr:
                ci.bases.append(attr)
        for item in ast.walk(node):
            if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
                # only direct methods (not nested-class methods)
                anc_classes = [a for a in mod.ancestors(item)
                               if isinstance(a, ast.ClassDef)]
                if anc_classes and anc_classes[0] is node:
                    fi = FunctionInfo(item.name, mod.qualname(item), mod,
                                      item, class_name=node.name)
                    ci.methods.setdefault(item.name, fi)
                    self.by_method_name.setdefault(item.name, []).append(fi)
            elif isinstance(item, ast.Assign) and isinstance(item.value,
                                                             ast.Call):
                kind = _is_lock_ctor(item.value)
                if kind:
                    for tgt in item.targets:
                        if (isinstance(tgt, ast.Attribute)
                                and isinstance(tgt.value, ast.Name)
                                and tgt.value.id == "self"):
                            ci.lock_attrs[tgt.attr] = kind
        self.classes.setdefault(node.name, []).append(ci)

    def _maybe_weakref_callback(self, mod: ModuleInfo,
                                call: ast.Call) -> None:
        """Record functions handed to weakref.ref(obj, cb) /
        weakref.finalize(obj, cb, ...) — they run in GC context exactly
        like __del__."""
        base, attr = _call_name(call.func)
        if attr == "ref" and base in ("weakref",) and len(call.args) >= 2:
            self.weakref_callbacks.append((call.args[1], mod))
        elif attr == "finalize" and base in ("weakref",) and len(
                call.args) >= 2:
            self.weakref_callbacks.append((call.args[1], mod))
        elif attr == "WeakValueDictionary":
            pass

    # ----------------------------------------------------------- lookup
    def class_of(self, fn: FunctionInfo) -> Optional[ClassInfo]:
        if not fn.class_name:
            return None
        for ci in self.classes.get(fn.class_name, []):
            if ci.module is fn.module:
                return ci
        return None

    def lock_kind(self, fn: FunctionInfo, expr: ast.AST) -> Tuple[
            Optional[str], str]:
        """Resolve a with-item / acquire() receiver to a lock (kind, name).

        kind is None when the expression is not a known lock.
        """
        if isinstance(expr, ast.Attribute) and isinstance(
                expr.value, ast.Name) and expr.value.id == "self":
            ci = self.class_of(fn)
            seen: Set[str] = set()
            while ci is not None and ci.name not in seen:
                seen.add(ci.name)
                if expr.attr in ci.lock_attrs:
                    return ci.lock_attrs[expr.attr], f"self.{expr.attr}"
                nxt = None
                for b in ci.bases:
                    cands = self.classes.get(b)
                    if cands:
                        nxt = cands[0]
                        break
                ci = nxt
            return None, f"self.{expr.attr}"
        if isinstance(expr, ast.Name):
            kind = self.module_locks.get((fn.module.relpath, expr.id))
            return kind, expr.id
        if isinstance(expr, ast.Call):
            kind = _is_lock_ctor(expr)
            if kind:
                return kind, "<inline lock>"
        return None, "<expr>"

    def lock_sites(self, fn: FunctionInfo) -> List[LockSite]:
        """Every lock acquisition (sync ``with`` or ``.acquire()``) in
        ``fn``'s own body (not nested defs)."""
        out: List[LockSite] = []
        for node in _own_body_walk(fn.node):
            if isinstance(node, ast.With):
                for item in node.items:
                    kind, name = self.lock_kind(fn, item.context_expr)
                    if kind:
                        out.append(LockSite(node, kind, name, fn))
            elif isinstance(node, ast.Call):
                base, attr = _call_name(node.func)
                if attr == "acquire" and isinstance(node.func,
                                                    ast.Attribute):
                    kind, name = self.lock_kind(fn, node.func.value)
                    if kind:
                        out.append(LockSite(node, kind, name, fn))
        return out

    # -------------------------------------------------------- call graph
    def resolve_call(self, fn: FunctionInfo,
                     call: ast.Call) -> List[FunctionInfo]:
        base, attr = _call_name(call.func)
        if attr is None:
            return []
        if base is None:  # bare name
            local = self.module_functions.get((fn.module.relpath, attr))
            if local is not None:
                return [local]
            cands = self.by_method_name.get(attr, [])
            cands = [c for c in cands if c.class_name is None]
            return cands if 0 < len(cands) <= AMBIGUITY_CUTOFF else []
        if base == "self":
            ci = self.class_of(fn)
            seen: Set[str] = set()
            while ci is not None and ci.name not in seen:
                seen.add(ci.name)
                if attr in ci.methods:
                    return [ci.methods[attr]]
                nxt = None
                for b in ci.bases:
                    cands2 = self.classes.get(b)
                    if cands2:
                        nxt = cands2[0]
                        break
                ci = nxt
            # fall through to global-by-name for mixin patterns
        if attr in GLOBAL_RESOLVE_BLOCKLIST:
            return []
        cands = self.by_method_name.get(attr, [])
        if 0 < len(cands) <= AMBIGUITY_CUTOFF:
            return cands
        return []

    def callees(self, fn: FunctionInfo) -> List[FunctionInfo]:
        out: List[FunctionInfo] = []
        for node in _own_body_walk(fn.node):
            if isinstance(node, ast.Call):
                out.extend(self.resolve_call(fn, node))
        return out

    def reachable(self, roots: List[FunctionInfo],
                  max_depth: int = 12) -> Dict[str, Tuple[FunctionInfo,
                                                          List[str]]]:
        """Fixpoint BFS from ``roots``; returns ref -> (fn, path-of-refs)
        so violations can explain *how* GC context reaches a lock."""
        frontier: List[Tuple[FunctionInfo, List[str]]] = [
            (r, [r.ref]) for r in roots]
        seen: Dict[str, Tuple[FunctionInfo, List[str]]] = {
            r.ref: (r, [r.ref]) for r in roots}
        depth = 0
        while frontier and depth < max_depth:
            nxt: List[Tuple[FunctionInfo, List[str]]] = []
            for fn, path in frontier:
                for callee in self.callees(fn):
                    if callee.ref not in seen:
                        npath = path + [callee.ref]
                        seen[callee.ref] = (callee, npath)
                        nxt.append((callee, npath))
            frontier = nxt
            depth += 1
        return seen

    def function_for_expr(self, expr: ast.AST,
                          mod: ModuleInfo) -> List[FunctionInfo]:
        """Resolve a callback expression (weakref.ref's 2nd arg) to
        project functions."""
        if isinstance(expr, ast.Name):
            fi = self.module_functions.get((mod.relpath, expr.id))
            if fi:
                return [fi]
            cands = self.by_method_name.get(expr.id, [])
            return cands if 0 < len(cands) <= AMBIGUITY_CUTOFF else []
        if isinstance(expr, ast.Attribute):
            cands = self.by_method_name.get(expr.attr, [])
            return cands if 0 < len(cands) <= AMBIGUITY_CUTOFF else []
        if isinstance(expr, ast.Lambda):
            # treat the lambda body's calls as roots via a synthetic fn
            return [FunctionInfo("<lambda>", f"{mod.qualname(expr)}.<lambda>",
                                 mod, expr)]
        return []


def _own_body_walk(fn_node: ast.AST):
    """Walk a function body without descending into nested function/class
    definitions (their bodies are separate call-graph nodes)."""
    if isinstance(fn_node, ast.Lambda):
        stack = [fn_node.body]
    else:
        body = getattr(fn_node, "body", None)
        if body is None:
            return
        stack = list(body)
    while stack:
        node = stack.pop()
        yield node
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef, ast.Lambda)):
            continue
        stack.extend(ast.iter_child_nodes(node))
