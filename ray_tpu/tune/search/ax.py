"""AxSearch adapter (reference: python/ray/tune/search/ax/ax_search.py —
wraps the Ax service API AxClient). Gated: `ax-platform` is not in this
image's baked package set — construction raises a clear ImportError."""

from __future__ import annotations

from typing import Dict, Optional

from ray_tpu.tune.search.sample import Categorical, Float, Integer
from ray_tpu.tune.search.searcher import Searcher


class AxSearch(Searcher):
    def __init__(self, space: Optional[Dict] = None,
                 metric: Optional[str] = None, mode: Optional[str] = None,
                 **kwargs):
        try:
            from ax.service.ax_client import AxClient  # noqa: F401
        except ImportError as e:
            raise ImportError(
                "AxSearch requires `ax-platform`, which is not installed "
                "in this environment. Use the native GP searcher "
                "(ray_tpu.tune.search.bayesopt) instead.") from e
        super().__init__(metric, mode)
        self._space = space or {}
        self._trials: Dict[str, int] = {}
        self._completed = 0
        self._build()

    def _build(self) -> None:
        from ax.service.ax_client import AxClient

        parameters = []
        self._constants: Dict[str, object] = {}
        for k, dom in self._space.items():
            if isinstance(dom, Categorical):
                parameters.append({"name": k, "type": "choice",
                                   "values": list(dom.categories)})
            elif isinstance(dom, Integer):
                parameters.append({"name": k, "type": "range",
                                   "bounds": [dom.lower, dom.upper - 1],
                                   "value_type": "int"})
            elif isinstance(dom, Float):
                parameters.append({
                    "name": k, "type": "range",
                    "bounds": [dom.lower, dom.upper],
                    "value_type": "float",
                    "log_scale": bool(getattr(dom, "log", False))})
            else:
                self._constants[k] = dom
        self._client = AxClient(verbose_logging=False)
        self._client.create_experiment(
            parameters=parameters,
            objective_name=self.metric or "objective",
            minimize=(self.mode == "min"))

    def set_search_properties(self, metric, mode, config) -> bool:
        """Adopt the Tuner-supplied metric/mode/param_space: Ax bakes the
        objective name AND direction into the experiment, so a rebuild is
        needed when they change — but ONLY then. Rebuilding whenever
        in-flight trials happened to be empty silently discarded the
        optimizer's accumulated observations between scheduling waves
        (reference: ax_search.py set_search_properties guards the same
        way)."""
        changed = (metric is not None and metric != self.metric) or \
            (mode is not None and mode != self.mode)
        super().set_search_properties(metric, mode, config)
        if config and not self._space:
            self._space = dict(config)
            changed = True
        if (changed or not self._completed) and not self._trials:
            self._build()
        return True

    def suggest(self, trial_id: str) -> Optional[Dict]:
        params, index = self._client.get_next_trial()
        self._trials[trial_id] = index
        out = dict(params)
        out.update(self._constants)
        return out

    def on_trial_complete(self, trial_id, result=None,
                          error: bool = False) -> None:
        index = self._trials.pop(trial_id, None)
        if index is None:
            return
        self._completed += 1  # any logged outcome is optimizer history
        if error or not result or self.metric not in result:
            self._client.log_trial_failure(index)
            return
        self._client.complete_trial(
            index, raw_data=float(result[self.metric]))
