"""Per-node Serve proxies: one controller-managed ProxyActor per node,
health-checked and restarted (reference: python/ray/serve/_private/
proxy.py:1097 per-node proxies + proxy_state.py ProxyStateManager —
VERDICT r4 #2: killing one node's proxy keeps traffic flowing on the
other node and the controller resurrects the dead one)."""

import json
import time
import urllib.request

import pytest

import ray_tpu
from ray_tpu import serve


@pytest.fixture(scope="module")
def serve_two_nodes():
    from ray_tpu.cluster_utils import Cluster

    cluster = Cluster(initialize_head=True, head_node_args={"num_cpus": 4})
    cluster.add_node(num_cpus=4)
    ray_tpu.init(_node=cluster.head_node)
    cluster.wait_for_nodes()
    serve.start(http_options={"port": 0})
    yield cluster
    serve.shutdown()
    ray_tpu.shutdown()
    cluster.shutdown()


def _http_get(port, path, timeout=30):
    with urllib.request.urlopen(
            f"http://127.0.0.1:{port}{path}", timeout=timeout) as r:
        return r.status, r.read()


def _wait_proxies(n, timeout=90):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        info = serve.get_proxy_info()
        healthy = {nid: p for nid, p in info.items() if p["healthy"]
                   and p["http_port"]}
        if len(healthy) >= n:
            return healthy
        time.sleep(0.5)
    raise TimeoutError(f"only {len(healthy)} healthy proxies, wanted {n}")


def test_proxy_per_node_and_failover(serve_two_nodes):
    @serve.deployment
    def hello(request):
        return {"msg": "hi"}

    serve.run(hello.bind(), name="hello", route_prefix="/hello")
    proxies = _wait_proxies(2)
    assert len(proxies) == 2, proxies

    # every node's proxy serves the app (routes arrive via long-poll)
    for nid, p in proxies.items():
        deadline = time.monotonic() + 30
        while True:
            try:
                status, body = _http_get(p["http_port"], "/hello")
                if status == 200:
                    break
            except Exception:
                if time.monotonic() > deadline:
                    raise
            time.sleep(0.3)
        assert json.loads(body) == {"msg": "hi"}

    # kill the proxy on the NON-driver node
    my_node = ray_tpu.get_runtime_context().get_node_id()
    victim_nid = next(nid for nid in proxies if nid != my_node)
    victim = proxies[victim_nid]
    survivor = proxies[my_node]
    ray_tpu.kill(ray_tpu.get_actor(victim["name"], namespace="serve"))

    # the surviving node's proxy keeps serving without interruption
    status, body = _http_get(survivor["http_port"], "/hello")
    assert status == 200 and json.loads(body) == {"msg": "hi"}

    # the controller health-checks and resurrects the dead node's proxy
    deadline = time.monotonic() + 90
    resurrected = None
    while time.monotonic() < deadline:
        info = serve.get_proxy_info()
        p = info.get(victim_nid)
        if p and p["healthy"] and p["name"] != victim["name"]:
            resurrected = p
            break
        time.sleep(0.5)
    assert resurrected is not None, "proxy was not restarted"

    # and the new proxy serves traffic again
    deadline = time.monotonic() + 30
    while True:
        try:
            status, body = _http_get(resurrected["http_port"], "/hello")
            if status == 200:
                break
        except Exception:
            if time.monotonic() > deadline:
                raise
        time.sleep(0.3)
    assert json.loads(body) == {"msg": "hi"}
    serve.delete("hello")


def test_new_node_gets_proxy(serve_two_nodes):
    """A node added AFTER serve.start gets its own proxy (reconcile loop
    tracks cluster membership)."""
    cluster = serve_two_nodes
    before = set(_wait_proxies(2))
    node = cluster.add_node(num_cpus=2)
    cluster.wait_for_nodes()
    try:
        proxies = _wait_proxies(3)
        new_nids = set(proxies) - before
        assert len(new_nids) == 1
    finally:
        cluster.remove_node(node)
