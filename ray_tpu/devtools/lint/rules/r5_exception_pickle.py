"""R5 — cross-process exceptions must survive a pickle round-trip.

Invariant: every exception class raised across a process boundary (the
public hierarchy in ``ray_tpu/exceptions.py``) must reconstruct with its
fields intact after ``pickle.dumps``/``loads``. The default
``BaseException.__reduce__`` re-calls ``cls(*self.args)`` — and
``self.args`` is whatever was passed to ``super().__init__()``, which in
a class with a custom ``__init__`` is almost always the *formatted
message*, not the original fields. The round trip then either crashes
(arity mismatch) or silently corrupts: the receiver catches
``ObjectLostError`` whose ``object_id_hex`` is a full sentence.

Motivating history: PR 5/6 added explicit ``__reduce__`` to the
``DeathContext`` carriers (``NodeDiedError``, ``RayActorError``,
``BackPressureError``) precisely because their context dicts evaporated
at the first boundary; this rule makes that discipline structural.

Detection (static half): in ``exceptions.py``, any class in the
exception hierarchy that defines (or inherits, within the module) a
custom ``__init__`` must also define or inherit-in-module a
``__reduce__``. The dynamic half is the auto-generated round-trip test
(tests/test_raylint.py) which instantiates every public class and
compares fields across dumps/loads.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set

from ..model import ModuleInfo, Violation

RULE_ID = "R5"
SUMMARY = ("exception class with a custom __init__ but no __reduce__ — "
           "default pickling rebuilds from self.args and drops/corrupts "
           "fields at the process boundary")

_TARGET_SUFFIX = "exceptions.py"


def check_module(mod: ModuleInfo, index) -> List[Violation]:
    if not mod.relpath.endswith(_TARGET_SUFFIX):
        return []
    classes: Dict[str, ast.ClassDef] = {
        n.name: n for n in mod.tree.body if isinstance(n, ast.ClassDef)}

    def bases_of(cd: ast.ClassDef) -> List[str]:
        out = []
        for b in cd.bases:
            if isinstance(b, ast.Name):
                out.append(b.id)
            elif isinstance(b, ast.Attribute):
                out.append(b.attr)
        return out

    def is_exception(name: str, seen: Optional[Set[str]] = None) -> bool:
        seen = seen or set()
        if name in seen:
            return False
        seen.add(name)
        if name in ("Exception", "BaseException", "TimeoutError",
                    "RuntimeError", "ValueError", "OSError"):
            return True
        cd = classes.get(name)
        if cd is None:
            return False
        return any(is_exception(b, seen) for b in bases_of(cd))

    def defines(cd: ast.ClassDef, meth: str) -> bool:
        return any(isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
                   and n.name == meth for n in cd.body)

    def inherits(name: str, meth: str, seen: Optional[Set[str]] = None
                 ) -> bool:
        """Does ``name`` define or inherit ``meth`` from an in-module
        ancestor?"""
        seen = seen or set()
        if name in seen:
            return False
        seen.add(name)
        cd = classes.get(name)
        if cd is None:
            return False
        if defines(cd, meth):
            return True
        return any(inherits(b, meth, seen) for b in bases_of(cd))

    out: List[Violation] = []
    for name, cd in classes.items():
        if not any(is_exception(b) for b in bases_of(cd)):
            continue
        if inherits(name, "__init__") and not inherits(name, "__reduce__"):
            out.append(mod.violation(
                RULE_ID, cd,
                f"exception '{name}' customizes __init__ (so self.args no "
                f"longer matches the constructor signature) but has no "
                f"__reduce__: pickling across a process boundary will "
                f"rebuild it from the formatted message, dropping or "
                f"corrupting its fields — add __reduce__ that rebuilds "
                f"from the real fields"))
    return out
