"""Lazy task/actor DAGs (reference: python/ray/dag/ — DAGNode
dag_node.py:25, InputNode/OutputNode, experimental CompiledDAG
compiled_dag_node.py:141).

``fn.bind(*args)`` builds the graph lazily; ``dag.execute(input)`` walks it,
submitting each node as a task with upstream ObjectRefs as args (so the
object store pipelines the whole graph without materializing on the
driver). ``dag.experimental_compile()`` returns a CompiledDAG that reuses
the same walk but keeps per-node submit order cached — the accelerated-DAG
analog; on TPU the intended use is chaining jitted stages whose arrays stay
in the object store between nodes.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

import ray_tpu


class DAGNode:
    def __init__(self, bound_args: tuple, bound_kwargs: dict):
        self._bound_args = bound_args
        self._bound_kwargs = bound_kwargs

    # ------------------------------------------------------------ execute
    def execute(self, *input_args, **input_kwargs):
        """Run the whole DAG; returns the final ObjectRef (or value for
        InputNode-only graphs)."""
        cache: Dict[int, Any] = {}
        return self._execute_node(cache, input_args, input_kwargs)

    def _resolve_arg(self, arg, cache, input_args, input_kwargs):
        if isinstance(arg, DAGNode):
            return arg._execute_node(cache, input_args, input_kwargs)
        return arg

    def _execute_node(self, cache, input_args, input_kwargs):
        raise NotImplementedError

    def experimental_compile(self) -> "CompiledDAG":
        return CompiledDAG(self)


class InputNode(DAGNode):
    """Placeholder for execute()-time input (reference: dag/input_node.py).

    Supports ``with InputNode() as inp:`` for API parity."""

    def __init__(self):
        super().__init__((), {})

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def _execute_node(self, cache, input_args, input_kwargs):
        if len(input_args) == 1 and not input_kwargs:
            return input_args[0]
        if input_kwargs and not input_args:
            return input_kwargs
        return input_args


class FunctionNode(DAGNode):
    def __init__(self, remote_fn, args: tuple, kwargs: dict):
        super().__init__(args, kwargs)
        self._remote_fn = remote_fn

    def _execute_node(self, cache, input_args, input_kwargs):
        key = id(self)
        if key not in cache:
            args = [self._resolve_arg(a, cache, input_args, input_kwargs)
                    for a in self._bound_args]
            kwargs = {k: self._resolve_arg(v, cache, input_args,
                                           input_kwargs)
                      for k, v in self._bound_kwargs.items()}
            cache[key] = self._remote_fn.remote(*args, **kwargs)
        return cache[key]


class ClassMethodNode(DAGNode):
    def __init__(self, actor_handle, method_name: str, args: tuple,
                 kwargs: dict, opts: Optional[dict] = None):
        super().__init__(args, kwargs)
        self._actor = actor_handle
        self._method_name = method_name
        self._opts = opts

    def _execute_node(self, cache, input_args, input_kwargs):
        key = id(self)
        if key not in cache:
            args = [self._resolve_arg(a, cache, input_args, input_kwargs)
                    for a in self._bound_args]
            kwargs = {k: self._resolve_arg(v, cache, input_args,
                                           input_kwargs)
                      for k, v in self._bound_kwargs.items()}
            method = getattr(self._actor, self._method_name)
            if self._opts:
                method = method.options(**self._opts)
            cache[key] = method.remote(*args, **kwargs)
        return cache[key]


class MultiOutputNode(DAGNode):
    """Terminal node collecting several branches
    (reference: dag/output_node.py)."""

    def __init__(self, outputs: List[DAGNode]):
        super().__init__(tuple(outputs), {})

    def _execute_node(self, cache, input_args, input_kwargs):
        return [self._resolve_arg(o, cache, input_args, input_kwargs)
                for o in self._bound_args]


class CompiledDAG:
    """Repeat-execution wrapper (reference: compiled_dag_node.py:141; the
    reference pre-allocates shared-memory channels — here the object store
    already pipelines refs, so compile just fixes the traversal order)."""

    def __init__(self, root: DAGNode):
        self._root = root

    def execute(self, *args, **kwargs):
        return self._root.execute(*args, **kwargs)

    def teardown(self) -> None:
        pass
