"""Base collective group (reference:
python/ray/util/collective/collective_group/base_collective_group.py).

Ops are *functional*: they return the result instead of mutating the input
tensor in place (the reference mutates torch/cupy tensors; jax arrays are
immutable, so the TPU-native API returns new values — numpy inputs are
additionally updated in place for drop-in compatibility).
"""

from __future__ import annotations

from abc import ABC, abstractmethod
from typing import Any, List

from ray_tpu.util.collective.types import (
    AllGatherOptions, AllReduceOptions, BarrierOptions, BroadcastOptions,
    RecvOptions, ReduceOptions, ReduceScatterOptions, SendOptions)


class BaseGroup(ABC):
    def __init__(self, world_size: int, rank: int, group_name: str):
        self._world_size = world_size
        self._rank = rank
        self._group_name = group_name

    @property
    def rank(self) -> int:
        return self._rank

    @property
    def world_size(self) -> int:
        return self._world_size

    @property
    def group_name(self) -> str:
        return self._group_name

    def destroy_group(self) -> None:
        pass

    @classmethod
    @abstractmethod
    def backend(cls) -> str:
        ...

    @abstractmethod
    def allreduce(self, tensor, opts: AllReduceOptions = AllReduceOptions()):
        ...

    @abstractmethod
    def barrier(self, opts: BarrierOptions = BarrierOptions()):
        ...

    @abstractmethod
    def reduce(self, tensor, opts: ReduceOptions = ReduceOptions()):
        ...

    @abstractmethod
    def allgather(self, tensor, opts: AllGatherOptions = AllGatherOptions()) -> List[Any]:
        ...

    @abstractmethod
    def broadcast(self, tensor, opts: BroadcastOptions = BroadcastOptions()):
        ...

    @abstractmethod
    def reducescatter(self, tensor_list, opts: ReduceScatterOptions = ReduceScatterOptions()):
        ...

    @abstractmethod
    def send(self, tensor, opts: SendOptions):
        ...

    @abstractmethod
    def recv(self, shape_dtype, opts: RecvOptions):
        ...
