"""Memory monitor / OOM killing policy tests (reference parity:
src/ray/common/memory_monitor_test.cc + worker_killing_policy tests and
python/ray/tests/test_memory_pressure.py)."""

import time

import pytest

from ray_tpu._private.memory_monitor import (
    MemoryMonitor,
    OomKiller,
    pick_oom_victim,
)


class TestMemoryMonitor:
    def test_usage_sane(self):
        used, total = MemoryMonitor().get_memory_usage()
        assert 0 < used < total

    def test_threshold(self):
        assert MemoryMonitor(usage_threshold=0.0).is_pressure()
        assert not MemoryMonitor(usage_threshold=1.0).is_pressure()

    def test_min_free_bytes(self):
        assert MemoryMonitor(min_memory_free_bytes=1 << 60).is_pressure()
        assert not MemoryMonitor(min_memory_free_bytes=1).is_pressure()


class TestVictimPolicy:
    def test_retriable_before_non_retriable(self):
        leases = [
            {"lease": "a", "retriable": False, "owner": "o1", "start": 1.0},
            {"lease": "b", "retriable": True, "owner": "o2", "start": 2.0},
        ]
        assert pick_oom_victim(leases)["lease"] == "b"

    def test_group_by_owner_hits_biggest_owner(self):
        leases = [
            {"lease": "a", "retriable": True, "owner": "big", "start": 1.0},
            {"lease": "b", "retriable": True, "owner": "big", "start": 2.0},
            {"lease": "c", "retriable": True, "owner": "small", "start": 0.5},
        ]
        v = pick_oom_victim(leases)
        assert v["owner"] == "big"
        assert v["lease"] == "b"  # youngest of the biggest owner

    def test_empty(self):
        assert pick_oom_victim([]) is None


class TestOomKiller:
    def test_kills_under_pressure_with_cooldown(self):
        killed = []
        leases = [{"lease": "x", "retriable": True, "owner": "o",
                   "start": 1.0}]
        k = OomKiller(MemoryMonitor(usage_threshold=0.0),
                      lambda: leases, lambda v: killed.append(v["lease"]),
                      cooldown_s=10.0)
        assert k.step()
        assert killed == ["x"]
        assert not k.step()  # cooldown blocks immediate re-kill

    def test_no_kill_without_pressure(self):
        k = OomKiller(MemoryMonitor(usage_threshold=1.0),
                      lambda: [{"lease": "x"}], lambda v: 1 / 0)
        assert not k.step()


def test_oom_killed_task_retries_end_to_end():
    """A leased task killed by the OOM killer must fail over to a retry
    (the owner-side max_retries path) and still complete."""
    import ray_tpu

    if not ray_tpu.is_initialized():
        ray_tpu.init(num_cpus=2)
    try:
        @ray_tpu.remote(max_retries=2)
        def slow_then_ok(marker):
            import os
            import time as t

            if not os.path.exists(marker):
                open(marker, "w").close()
                t.sleep(30)  # stays leased long enough to be "killed"
            return "survived"

        import os
        import signal
        import subprocess
        import tempfile

        session_dir = ray_tpu._global_node.session_dir
        marker = tempfile.mktemp()
        ref = slow_then_ok.remote(marker)
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline and not os.path.exists(marker):
            time.sleep(0.25)
        assert os.path.exists(marker), "task never started"
        # SIGTERM this session's workers — exactly what OomKiller.kill does
        out = subprocess.run(["pgrep", "-f", "worker_process"],
                             capture_output=True, text=True)
        for pid in (int(p) for p in out.stdout.split()):
            try:
                with open(f"/proc/{pid}/environ", "rb") as f:
                    env = f.read().decode("utf-8", "replace")
            except OSError:
                continue
            if session_dir in env:
                try:
                    os.kill(pid, signal.SIGTERM)
                except OSError:
                    pass
        assert ray_tpu.get(ref, timeout=120) == "survived"
    finally:
        ray_tpu.shutdown()
