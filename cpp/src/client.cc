// ray_tpu C++ driver client — implementation. See client.hpp for scope.

#include "ray_tpu/client.hpp"

#include <arpa/inet.h>
#include <netdb.h>
#include <sys/socket.h>
#include <sys/time.h>
#include <unistd.h>

#include <algorithm>
#include <cstring>
#include <random>
#include <stdexcept>

namespace ray_tpu {

using msgpack::Value;

// ------------------------------------------------------------- RpcClient

RpcClient::~RpcClient() { Close(); }

void RpcClient::Close() {
  if (fd_ >= 0) {
    ::close(fd_);
    fd_ = -1;
  }
}

void RpcClient::Connect(const std::string& host, int port,
                        double timeout_s) {
  Close();
  struct addrinfo hints;
  std::memset(&hints, 0, sizeof(hints));
  hints.ai_family = AF_INET;
  hints.ai_socktype = SOCK_STREAM;
  struct addrinfo* res = nullptr;
  const std::string port_str = std::to_string(port);
  // the agents bind 0.0.0.0 and advertise it back verbatim for local
  // clusters; loopback is the reachable address in that case
  const std::string target =
      (host == "0.0.0.0" || host.empty()) ? "127.0.0.1" : host;
  if (::getaddrinfo(target.c_str(), port_str.c_str(), &hints, &res) != 0 ||
      res == nullptr)
    throw std::runtime_error("ray_tpu: cannot resolve " + target);
  fd_ = ::socket(res->ai_family, res->ai_socktype, res->ai_protocol);
  if (fd_ < 0) {
    ::freeaddrinfo(res);
    throw std::runtime_error("ray_tpu: socket() failed");
  }
  struct timeval tv;
  tv.tv_sec = static_cast<long>(timeout_s);
  tv.tv_usec = static_cast<long>((timeout_s - tv.tv_sec) * 1e6);
  ::setsockopt(fd_, SOL_SOCKET, SO_RCVTIMEO, &tv, sizeof(tv));
  ::setsockopt(fd_, SOL_SOCKET, SO_SNDTIMEO, &tv, sizeof(tv));
  int ok = ::connect(fd_, res->ai_addr, res->ai_addrlen);
  ::freeaddrinfo(res);
  if (ok != 0) {
    Close();
    throw std::runtime_error("ray_tpu: connect to " + target + ":" +
                             port_str + " failed");
  }
  int nodelay = 1;
  ::setsockopt(fd_, IPPROTO_TCP, 1 /*TCP_NODELAY*/, &nodelay,
               sizeof(nodelay));
}

void RpcClient::send_all(const std::string& data) {
  size_t off = 0;
  while (off < data.size()) {
    ssize_t n = ::send(fd_, data.data() + off, data.size() - off, 0);
    if (n <= 0) {
      Close();
      throw std::runtime_error("ray_tpu: send failed");
    }
    off += static_cast<size_t>(n);
  }
}

std::string RpcClient::read_frame() {
  auto read_exact = [&](size_t n) {
    while (inbuf_.size() < n) {
      char buf[65536];
      ssize_t got = ::recv(fd_, buf, sizeof(buf), 0);
      if (got <= 0) {
        Close();
        throw std::runtime_error("ray_tpu: connection closed by peer");
      }
      inbuf_.append(buf, static_cast<size_t>(got));
    }
  };
  read_exact(4);
  uint32_t len;
  std::memcpy(&len, inbuf_.data(), 4);  // u32 little-endian, like the wire
  read_exact(4 + len);
  std::string body = inbuf_.substr(4, len);
  inbuf_.erase(0, 4 + len);
  return body;
}

Value RpcClient::Call(const std::string& method, const Value& payload) {
  if (fd_ < 0) throw std::runtime_error("ray_tpu: not connected");
  const uint32_t req_id = next_id_++;
  Value frame = Value::Map();
  frame.Set("m", Value::Str(method));
  frame.Set("i", Value::Int(req_id));
  frame.Set("p", payload);
  std::string body = msgpack::Pack(frame);
  std::string out(4, '\0');
  uint32_t len = static_cast<uint32_t>(body.size());
  std::memcpy(&out[0], &len, 4);
  out += body;
  send_all(out);
  for (;;) {
    Value reply = msgpack::Unpack(read_frame());
    const Value* r = reply.Find("r");
    if (!r) continue;  // server push ({"m": ...}); this client ignores them
    if (r->AsInt() != static_cast<int64_t>(req_id)) continue;  // stale
    const Value* err = reply.Find("e");
    if (err && !err->is_nil()) {
      std::string msg = "remote error";
      if (err->type == Value::Type::Array && err->arr.size() >= 2)
        msg = err->arr[0].AsStr() + ": " + err->arr[1].AsStr();
      throw std::runtime_error("ray_tpu RPC " + method + ": " + msg);
    }
    const Value* p = reply.Find("p");
    return p ? *p : Value::Nil();
  }
}

// ------------------------------------------------------------- RayClient

namespace {

constexpr int64_t kFixedPointScale = 10000;  // resources.py granularity

std::string RandomBytes(size_t n) {
  static thread_local std::mt19937_64 rng{std::random_device{}()};
  std::string out(n, '\0');
  for (size_t k = 0; k < n; ++k)
    out[k] = static_cast<char>(rng() & 0xff);
  return out;
}

// the Python-side cross-language sentinel
// (ray_tpu/_private/function_table.py XLANG_PYREF_FID, 16 bytes)
const char kXlangFid[] = "xlang-pyref\x00\x00\x00\x00\x00";

}  // namespace

void RayClient::Connect(const std::string& head_host, int head_port) {
  head_.Connect(head_host, head_port);
  job_id_ = RandomBytes(4);
  // announce ourselves like a Python driver would so the job shows up in
  // the job table / dashboard
  Value reg = Value::Map();
  reg.Set("job_id", Value::Str("cpp-" + std::to_string(head_port)));
  reg.Set("entrypoint", Value::Str("cpp-driver"));
  try {
    head_.Call("RegisterDriver", reg);
  } catch (const std::exception&) {
    // registration is observability, not a functional dependency
  }
}

bool RayClient::KvPut(const std::string& key, const std::string& value,
                      bool overwrite, const std::string& ns) {
  Value p = Value::Map();
  p.Set("ns", Value::Str(ns));
  p.Set("key", Value::Bin(key));
  p.Set("value", Value::Bin(value));
  p.Set("overwrite", Value::Boolean(overwrite));
  Value r = head_.Call("KvPut", p);
  return r.type == Value::Type::Bool && r.b;
}

Value RayClient::KvGet(const std::string& key, const std::string& ns) {
  Value p = Value::Map();
  p.Set("ns", Value::Str(ns));
  p.Set("key", Value::Bin(key));
  return head_.Call("KvGet", p);
}

Value RayClient::ClusterView() {
  return head_.Call("GetClusterView", Value::Map());
}

RpcClient& RayClient::AgentAt(const std::string& host, int port) {
  for (auto& a : agents_)
    if (a.host == host && a.port == port && a.client->connected())
      return *a.client;
  // drop dead entries so reconnect churn doesn't grow the cache forever
  agents_.erase(
      std::remove_if(agents_.begin(), agents_.end(),
                     [](const AgentConn& a) {
                       return !a.client->connected();
                     }),
      agents_.end());
  AgentConn conn{host, port, std::unique_ptr<RpcClient>(new RpcClient())};
  conn.client->Connect(host, port, 60.0);
  agents_.push_back(std::move(conn));
  return *agents_.back().client;
}

Value RayClient::SubmitPyTask(const std::string& func_ref,
                              const std::vector<Value>& args,
                              const TaskOptions& opts) {
  // ---- pick a node (first alive) -------------------------------------
  Value view = ClusterView();
  const Value* addr = nullptr;
  for (const auto& kv : view.map) {
    const Value* alive = kv.second.Find("alive");
    if (alive && alive->type == Value::Type::Bool && !alive->b) continue;
    addr = kv.second.Find("addr");
    if (addr) break;
  }
  if (!addr) throw std::runtime_error("ray_tpu: no alive nodes");

  // ---- lease a worker (agent RequestWorkerLease, spillback-following) -
  Value lease_payload = Value::Map();
  Value resources = Value::Map();
  resources.Set("CPU", Value::Int(static_cast<int64_t>(
      opts.num_cpus * kFixedPointScale)));
  lease_payload.Set("resources", resources);
  lease_payload.Set("owner", Value::Str("cpp-driver"));
  lease_payload.Set("retriable", Value::Boolean(false));
  std::string host = addr->At("host").AsStr();
  int port = static_cast<int>(addr->At("port").AsInt());
  Value reply;
  for (int hop = 0; hop < 5; ++hop) {
    RpcClient& agent = AgentAt(host, port);
    reply = agent.Call("RequestWorkerLease", lease_payload);
    const Value* spill = reply.Find("spillback");
    if (!spill || spill->is_nil()) break;
    host = spill->At("addr").At("host").AsStr();
    port = static_cast<int>(spill->At("addr").At("port").AsInt());
    lease_payload.Set("spilled_once", Value::Boolean(true));
  }
  {
    const Value* spill = reply.Find("spillback");
    if (spill && !spill->is_nil())
      throw std::runtime_error(
          "ray_tpu: no worker lease granted after max spillback hops "
          "(cluster busy)");
  }
  const Value* error = reply.Find("error");
  if (error && !error->is_nil()) {
    const Value* msg = reply.Find("message");
    throw std::runtime_error("ray_tpu lease error: " +
                             (msg ? msg->AsStr() : error->AsStr()));
  }
  const Value& grant = reply.At("grant");
  const Value& waddr = grant.At("addr");

  // ---- push the cross-language spec directly to the leased worker -----
  RpcClient worker;
  worker.Connect(waddr.At("host").AsStr(),
                 static_cast<int>(waddr.At("port").AsInt()), 600.0);
  ++task_counter_;
  Value spec = Value::Map();
  spec.Set("task_id", Value::Bin(RandomBytes(16)));
  spec.Set("job_id", Value::Bin(job_id_));
  spec.Set("task_type", Value::Int(0));  // NORMAL_TASK
  spec.Set("function_id", Value::Bin(std::string(kXlangFid, 16)));
  spec.Set("function_name", Value::Str(func_ref));
  Value wire_args = Value::Array();
  for (const auto& a : args) {
    Value entry = Value::Array();
    entry.arr.push_back(Value::Str("x"));
    entry.arr.push_back(Value::Bin(msgpack::Pack(a)));
    wire_args.arr.push_back(std::move(entry));
  }
  spec.Set("args", std::move(wire_args));
  spec.Set("kwargs", Value::Map());
  spec.Set("num_returns", Value::Int(opts.num_returns));
  spec.Set("resources", Value::Map());
  Value owner = Value::Map();
  owner.Set("host", Value::Str(""));
  owner.Set("port", Value::Int(0));
  owner.Set("worker_id", Value::Str(std::string(32, '0')));
  spec.Set("owner_addr", std::move(owner));
  Value result = worker.Call("PushTask", spec);

  // ---- return the lease, then decode ---------------------------------
  Value ret_payload = Value::Map();
  ret_payload.Set("lease_id", grant.At("lease_id"));
  try {
    AgentAt(host, port).Call("ReturnWorker", ret_payload);
  } catch (const std::exception&) {
    // lease reaping on the agent side covers a lost return
  }
  const Value* err = result.Find("error");
  if (err && !err->is_nil() && !(err->type == Value::Type::Bool && !err->b)) {
    const Value* msg = result.Find("error_message");
    throw std::runtime_error(
        "ray_tpu task failed: " +
        (msg ? msg->AsStr() : std::string("(no message)")));
  }
  const Value& returns = result.At("returns");
  if (returns.arr.empty()) return Value::Nil();
  const Value* xl = returns.arr[0].Find("xlang");
  if (!xl)
    throw std::runtime_error(
        "ray_tpu: worker returned a non-cross-language payload");
  return msgpack::Unpack(xl->AsStr());
}

}  // namespace ray_tpu
