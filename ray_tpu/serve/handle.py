"""DeploymentHandle + Router (reference: python/ray/serve/handle.py:613 —
``remote`` :685; _private/router.py:37; power-of-two-choices replica
scheduling replica_scheduler/pow_2_scheduler.py:44 on CACHED queue depths).

``handle.remote(*args)`` returns a ``DeploymentResponse``; resolution picks
two random replicas and sends to the one with the lower cached queue depth
(depths piggyback on every reply — no per-request probe RPCs; a cold cache
falls back to random choice). A replica whose admission queue is full sheds
the request; the router tries the remaining replicas once each and then
raises a typed ``BackPressureError`` instead of spin-retrying.
"""

from __future__ import annotations

import asyncio
import concurrent.futures
import random
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

import ray_tpu
from ray_tpu.exceptions import BackPressureError, RayTaskError
from ray_tpu.serve._private.controller import SERVE_NAMESPACE
from ray_tpu.serve._private.replica import SHED

# Shared bounded pool driving request resolution: one task per in-flight
# handle.remote(), instead of an unbounded thread per request. Daemon
# threads (unlike ThreadPoolExecutor's) so stranded requests never block
# interpreter exit.
class _DaemonPool:
    MAX_WORKERS = 64

    def __init__(self):
        import collections

        self._q: "collections.deque" = collections.deque()
        self._cv = threading.Condition()
        self._threads = 0
        self._idle = 0  # exact count of threads blocked in wait()

    def submit(self, fn, *args):
        fut: "concurrent.futures.Future" = concurrent.futures.Future()
        with self._cv:
            self._q.append((fut, fn, args))
            if self._idle >= len(self._q):
                # enough waiters to claim every queued item
                self._cv.notify()
            elif self._threads < self.MAX_WORKERS:
                self._threads += 1
                threading.Thread(
                    target=self._run, name="serve-handle", daemon=True
                ).start()
            else:
                self._cv.notify()  # saturated: item waits for a free thread
        return fut

    def _run(self):
        while True:
            with self._cv:
                while not self._q:
                    self._idle += 1
                    self._cv.wait()
                    self._idle -= 1
                fut, fn, args = self._q.popleft()
            if not fut.set_running_or_notify_cancel():
                continue
            try:
                fut.set_result(fn(*args))
            except BaseException as e:
                fut.set_exception(e)


_request_pool: Optional[_DaemonPool] = None
_request_pool_lock = threading.Lock()


def _get_request_pool() -> _DaemonPool:
    global _request_pool
    with _request_pool_lock:
        if _request_pool is None:
            _request_pool = _DaemonPool()
        return _request_pool


class _ReplicaSet:
    """Cached replica handles for one deployment, refreshed from the
    controller (long-poll on change, TTL fallback)."""

    TTL_S = 2.0

    def __init__(self, app_name: str, dep_name: str):
        self.app_name = app_name
        self.dep_name = dep_name
        self._snapshot_id = 0
        self._handles: Dict[str, Any] = {}
        self._names: List[str] = []
        self._last_refresh = 0.0
        self._lock = threading.Lock()

    def _controller(self):
        from ray_tpu.serve._private.controller import (
            CONTROLLER_NAME, SERVE_NAMESPACE as NS)

        return ray_tpu.get_actor(CONTROLLER_NAME, namespace=NS)

    def refresh(self, force: bool = False) -> None:
        with self._lock:
            now = time.monotonic()
            if not force and now - self._last_refresh < self.TTL_S:
                return
            self._last_refresh = now
            ctrl = self._controller()
            sid, names = ray_tpu.get(
                ctrl.list_replica_names.remote(self.app_name, self.dep_name),
                timeout=30)
            if sid == self._snapshot_id:
                return
            self._snapshot_id = sid
            self._names = names
            self._handles = {n: h for n, h in self._handles.items()
                             if n in names}

    def handles(self) -> List[Tuple[str, Any]]:
        self.refresh()
        out = []
        for n in self._names:
            h = self._handles.get(n)
            if h is None:
                try:
                    h = ray_tpu.get_actor(n, namespace=SERVE_NAMESPACE)
                    self._handles[n] = h
                except Exception:
                    continue
            out.append((n, h))
        return out


class Router:
    """Pow-2 choice over piggybacked queue depths + typed shed."""

    # piggybacked depths go stale as OTHER routers send traffic; past the
    # TTL a cached depth is no better than random choice
    DEPTH_TTL_S = 5.0

    def __init__(self, app_name: str, dep_name: str):
        self.replica_set = _ReplicaSet(app_name, dep_name)
        self._depths: Dict[str, Tuple[int, float]] = {}  # name -> (depth, t)
        self._depth_lock = threading.Lock()

    def _note_depth(self, name: str, depth: Any) -> None:
        if not isinstance(depth, (int, float)):
            return
        with self._depth_lock:
            self._depths[name] = (int(depth), time.monotonic())
            if len(self._depths) > 4 * max(1, len(self.replica_set._names)):
                # drop entries for replicas long gone
                live = set(self.replica_set._names)
                for n in list(self._depths):
                    if n not in live:
                        del self._depths[n]

    def _cached_depth(self, name: str) -> Optional[int]:
        with self._depth_lock:
            rec = self._depths.get(name)
        if rec is None or time.monotonic() - rec[1] > self.DEPTH_TTL_S:
            return None
        return rec[0]

    def _pick(self, handles: List[Tuple[str, Any]],
              exclude: Optional[set] = None) -> Optional[Tuple[str, Any]]:
        """Two random candidates, lower cached depth wins; cold cache (no
        fresh depth for either) falls back to random — never a probe RPC
        on the request path."""
        pool = [h for h in handles if not exclude or h[0] not in exclude]
        if not pool:
            return None
        if len(pool) == 1:
            return pool[0]
        a, b = random.sample(pool, 2)
        da, db = self._cached_depth(a[0]), self._cached_depth(b[0])
        if da is None and db is None:
            return random.choice((a, b))
        if da is None:
            return a  # unknown: optimistically assume idle (it gets a
            # request either way, and its reply warms the cache)
        if db is None:
            return b
        return a if da <= db else b

    def _backpressure(self) -> BackPressureError:
        with self._depth_lock:
            depths = {n: d for n, (d, _) in self._depths.items()}
        return BackPressureError(
            deployment=f"{self.replica_set.app_name}#"
                       f"{self.replica_set.dep_name}",
            queue_depths=depths)

    def assign(self, method_name: Optional[str], args, kwargs,
               multiplexed_model_id: str = "",
               timeout: Optional[float] = None) -> Any:
        deadline = time.monotonic() + (timeout if timeout is not None
                                       else 60.0)
        backoff = 0.02
        shed_by: set = set()
        while True:
            handles = self.replica_set.handles()
            if not handles:
                if time.monotonic() > deadline:
                    raise TimeoutError(
                        f"no replicas for {self.replica_set.app_name}#"
                        f"{self.replica_set.dep_name}")
                time.sleep(backoff)
                backoff = min(backoff * 2, 0.5)
                self.replica_set.refresh(force=True)
                continue
            picked = self._pick(handles, exclude=shed_by)
            if picked is None:
                # every live replica shed this request: typed backpressure,
                # not a spin-retry loop (clients own the retry policy)
                raise self._backpressure()
            name, replica = picked
            try:
                # ttl rides along so a request still parked in the
                # admission queue when this get's deadline passes is shed
                # at admission instead of running user code the client
                # already gave up on (double side effects on retry)
                remaining = max(0.5, deadline - time.monotonic())
                reply = ray_tpu.get(
                    replica.handle_request.remote(
                        method_name, args, kwargs, multiplexed_model_id,
                        remaining),
                    timeout=remaining)
            except RayTaskError:
                # deterministic application error from user code: surface
                # immediately, do NOT re-execute (side effects!)
                raise
            except Exception:
                # transport/replica-death errors: retry elsewhere
                if time.monotonic() > deadline:
                    raise
                self.replica_set.refresh(force=True)
                time.sleep(backoff)
                backoff = min(backoff * 2, 0.5)
                continue
            kind = reply[0] if isinstance(reply, tuple) else None
            if kind is not None and len(reply) > 2:
                self._note_depth(name, reply[2])
            if kind == SHED:
                shed_by.add(name)
                continue
            if kind == "stream":
                # generator endpoint: re-issue through the streaming path
                # (the replica detected this before running user code)
                return _BufferedStream(
                    self.assign_streaming(method_name, args, kwargs,
                                          multiplexed_model_id, timeout))
            if kind == "stream_buffered":
                meta = reply[1]
                return _BufferedStream(
                    iter([("start", {k: meta[k] for k in
                                     ("status_code", "media_type",
                                      "headers")})] +
                         [("chunk", c) for c in meta["chunks"]]))
            return reply[1]

    def assign_streaming(self, method_name: Optional[str], args, kwargs,
                         multiplexed_model_id: str = "",
                         timeout: Optional[float] = None):
        """Streaming variant: yields ('start', meta) then ('chunk', value)
        items as the replica produces them (reference: router.py streaming
        assignment feeding DeploymentResponseGenerator)."""
        deadline = time.monotonic() + (timeout if timeout is not None
                                       else 60.0)
        backoff = 0.02
        shed_by: set = set()
        while True:
            handles = self.replica_set.handles()
            if not handles:
                if time.monotonic() > deadline:
                    raise TimeoutError(
                        f"no replicas for {self.replica_set.app_name}#"
                        f"{self.replica_set.dep_name}")
                time.sleep(backoff)
                backoff = min(backoff * 2, 0.5)
                self.replica_set.refresh(force=True)
                continue
            picked = self._pick(handles, exclude=shed_by)
            if picked is None:
                raise self._backpressure()
            name, replica = picked
            try:
                remaining = max(0.5, deadline - time.monotonic())
                gen = replica.handle_request_streaming.options(
                    num_returns="streaming").remote(
                        method_name, args, kwargs, multiplexed_model_id,
                        remaining)
                it = iter(gen)
                first_ref = next(it)
                first = ray_tpu.get(first_ref, timeout=remaining)
            except RayTaskError:
                raise
            except StopIteration:
                raise RuntimeError("streaming replica produced no handshake")
            except Exception:
                if time.monotonic() > deadline:
                    raise
                self.replica_set.refresh(force=True)
                time.sleep(backoff)
                backoff = min(backoff * 2, 0.5)
                continue
            if isinstance(first, tuple) and first[0] == SHED:
                if len(first) > 2:
                    self._note_depth(name, first[2])
                shed_by.add(name)
                continue
            if isinstance(first, tuple) and first[0] == "start":
                self._note_depth(name, first[1].get("queue_depth"))

            def stream():
                yield first
                for ref in it:
                    yield ray_tpu.get(ref)

            return stream()


class _BufferedStream:
    """Iterator over ('start', meta)/('chunk', value) items exposing the
    response metadata and plain chunk values."""

    def __init__(self, items):
        self._items = iter(items)
        first = next(self._items, None)
        if first is not None and first[0] == "start":
            self.meta = first[1]
            self._pending = None
        else:
            self.meta = {"status_code": 200, "media_type": None,
                         "headers": {}}
            self._pending = first

    def __iter__(self):
        if self._pending is not None:
            kind, value = self._pending
            self._pending = None
            if kind == "chunk":
                yield value
        for kind, value in self._items:
            if kind == "chunk":
                yield value


class DeploymentResponse:
    """Lazy result of ``handle.remote`` (reference: handle.py
    DeploymentResponse). ``result()`` blocks; ``await response`` works in
    async deployments."""

    def __init__(self, router: Router, method_name: Optional[str],
                 args, kwargs, multiplexed_model_id: str = ""):
        self._router = router
        self._method_name = method_name
        self._args = args
        self._kwargs = kwargs
        self._model_id = multiplexed_model_id
        self._future = _get_request_pool().submit(
            self._router.assign, self._method_name, self._args,
            self._kwargs, self._model_id)

    def result(self, timeout_s: Optional[float] = None) -> Any:
        try:
            return self._future.result(timeout_s)
        except concurrent.futures.TimeoutError:
            if self._future.done():
                # completed in the race window after the wait timed out —
                # surface the real outcome (a value, or the request's own
                # TimeoutError with its diagnostic message)
                return self._future.result(0)
            raise TimeoutError("request did not complete in time")

    def __await__(self):
        return asyncio.to_thread(self.result).__await__()


class DeploymentResponseGenerator:
    """Streaming result of ``handle.options(stream=True).remote()``
    (reference: handle.py DeploymentResponseGenerator): a sync iterator of
    chunk values, produced as the replica yields them."""

    def __init__(self, router: Router, method_name: Optional[str],
                 args, kwargs, multiplexed_model_id: str = ""):
        self._future = _get_request_pool().submit(
            router.assign_streaming, method_name, args, kwargs,
            multiplexed_model_id)
        self._stream = None

    def _ensure(self, timeout_s: Optional[float] = 60.0):
        if self._stream is None:
            self._stream = _BufferedStream(self._future.result(timeout_s))
        return self._stream

    @property
    def meta(self) -> Dict:
        return self._ensure().meta

    def __iter__(self):
        return iter(self._ensure())


class DeploymentHandle:
    def __init__(self, app_name: str, dep_name: str,
                 method_name: Optional[str] = None,
                 multiplexed_model_id: str = "", stream: bool = False):
        self.app_name = app_name
        self.deployment_name = dep_name
        self._method_name = method_name
        self._model_id = multiplexed_model_id
        self._stream = stream
        self._router: Optional[Router] = None

    def _get_router(self) -> Router:
        if self._router is None:
            self._router = Router(self.app_name, self.deployment_name)
        return self._router

    def options(self, *, method_name: Optional[str] = None,
                multiplexed_model_id: Optional[str] = None,
                stream: Optional[bool] = None) -> "DeploymentHandle":
        h = DeploymentHandle(
            self.app_name, self.deployment_name,
            method_name or self._method_name,
            multiplexed_model_id if multiplexed_model_id is not None
            else self._model_id,
            self._stream if stream is None else stream)
        h._router = self._router
        return h

    def __getattr__(self, item):
        if item.startswith("_"):
            raise AttributeError(item)
        return DeploymentHandle(self.app_name, self.deployment_name, item,
                                self._model_id, self._stream)

    def remote(self, *args, **kwargs):
        if self._stream:
            return DeploymentResponseGenerator(
                self._get_router(), self._method_name, args, kwargs,
                self._model_id)
        return DeploymentResponse(
            self._get_router(), self._method_name, args, kwargs,
            self._model_id)

    def __reduce__(self):
        return (DeploymentHandle,
                (self.app_name, self.deployment_name, self._method_name,
                 self._model_id, self._stream))
