"""Autoscaler tests (reference parity: python/ray/tests/test_autoscaler.py
and test_autoscaling_cluster — scale-up on demand, min_workers, idle
scale-down, bin-packing unit tests)."""

import time

import pytest

from ray_tpu._private.resources import ResourceSet
from ray_tpu.autoscaler.resource_demand_scheduler import get_nodes_to_launch


def _w(d):
    return ResourceSet(d).to_wire()


@pytest.fixture(params=["native", "python"])
def sched_backend(request, monkeypatch):
    """Both the C++ kernel (sched.cc) and the pure-Python fallback must
    produce the same packing decisions."""
    monkeypatch.setenv("RAY_TPU_NATIVE_SCHED",
                       "1" if request.param == "native" else "0")
    return request.param


class TestBinPacking:
    NODE_TYPES = {
        "cpu4": {"resources": {"CPU": 4}, "max_workers": 10},
        "tpu_slice": {"resources": {"TPU": 4, "CPU": 8}, "max_workers": 4},
    }

    def test_no_demand_no_launch(self, sched_backend):
        assert get_nodes_to_launch(self.NODE_TYPES, [], [], {}, 8, 0) == {}

    def test_demand_fits_existing(self, sched_backend):
        out = get_nodes_to_launch(
            self.NODE_TYPES, [_w({"CPU": 2})], [_w({"CPU": 4})], {}, 8, 1)
        assert out == {}

    def test_launch_for_unfulfilled(self, sched_backend):
        out = get_nodes_to_launch(
            self.NODE_TYPES, [_w({"CPU": 2})], [], {}, 8, 0)
        assert out == {"cpu4": 1}

    def test_pack_multiple_onto_one_node(self, sched_backend):
        out = get_nodes_to_launch(
            self.NODE_TYPES, [_w({"CPU": 2})] * 2, [], {}, 8, 0)
        assert out == {"cpu4": 1}

    def test_tpu_demand_picks_tpu_type(self, sched_backend):
        out = get_nodes_to_launch(
            self.NODE_TYPES, [_w({"TPU": 4})], [_w({"CPU": 4})], {}, 8, 1)
        assert out == {"tpu_slice": 1}

    def test_max_workers_cap(self, sched_backend):
        out = get_nodes_to_launch(
            self.NODE_TYPES, [_w({"CPU": 4})] * 5, [], {}, 2, 0)
        assert sum(out.values()) <= 2

    def test_infeasible_demand_ignored(self, sched_backend):
        out = get_nodes_to_launch(
            self.NODE_TYPES, [_w({"GPU": 1})], [], {}, 8, 0)
        assert out == {}

    def test_per_type_max(self, sched_backend):
        types = {"cpu4": {"resources": {"CPU": 4}, "max_workers": 1}}
        out = get_nodes_to_launch(
            types, [_w({"CPU": 4})] * 3, [], {}, 8, 0)
        assert out == {"cpu4": 1}


class TestAutoscalingCluster:
    def test_scale_up_and_down(self):
        import ray_tpu
        from ray_tpu.cluster_utils import AutoscalingCluster

        cluster = AutoscalingCluster(
            head_resources={"CPU": 1},
            worker_node_types={
                "worker": {"resources": {"CPU": 2, "extra": 2},
                           "min_workers": 0, "max_workers": 2},
            },
            idle_timeout_minutes=0.03,  # ~2s
            update_interval_s=0.3,
        )
        cluster.start()
        try:
            ray_tpu.init(address=cluster.address)

            @ray_tpu.remote(resources={"extra": 1})
            def on_worker():
                return "scaled"

            # no worker node exists yet: this demand must trigger scale-up
            assert ray_tpu.get(on_worker.remote(), timeout=120) == "scaled"
            assert cluster.provider.non_terminated_nodes()

            # idle: the worker node should be terminated after the timeout
            deadline = time.monotonic() + 60
            while time.monotonic() < deadline:
                if not cluster.provider.non_terminated_nodes():
                    break
                time.sleep(0.5)
            assert not cluster.provider.non_terminated_nodes(), \
                "idle node was not scaled down"
        finally:
            ray_tpu.shutdown()
            cluster.shutdown()

    def test_min_workers_maintained(self):
        import ray_tpu
        from ray_tpu.cluster_utils import AutoscalingCluster

        cluster = AutoscalingCluster(
            head_resources={"CPU": 1},
            worker_node_types={
                "worker": {"resources": {"CPU": 2},
                           "min_workers": 1, "max_workers": 2},
            },
            idle_timeout_minutes=0.02,
            update_interval_s=0.3,
        )
        cluster.start()
        try:
            ray_tpu.init(address=cluster.address)
            deadline = time.monotonic() + 60
            while time.monotonic() < deadline:
                if len(cluster.provider.non_terminated_nodes()) >= 1:
                    break
                time.sleep(0.5)
            assert len(cluster.provider.non_terminated_nodes()) >= 1
            # idle min_workers node must NOT be reclaimed
            time.sleep(3)
            assert len(cluster.provider.non_terminated_nodes()) >= 1
        finally:
            ray_tpu.shutdown()
            cluster.shutdown()


class TestNativeSchedulerKernel:
    def test_best_node_prefers_low_utilization(self):
        pytest.importorskip("ray_tpu._native")
        from ray_tpu._native import NativeScheduler, get_native_lib

        if get_native_lib() is None:
            pytest.skip("native toolchain unavailable")
        s = NativeScheduler()
        idx = s.best_node(
            avail_rows=[{"CPU": 1}, {"CPU": 4}],
            total_rows=[{"CPU": 4}, {"CPU": 4}],
            request={"CPU": 1})
        assert idx == 1  # emptier node wins
        assert s.best_node([{"CPU": 1}], [{"CPU": 1}], {"GPU": 1}) == -1

    def test_fuzz_native_matches_python(self, monkeypatch):
        """Random demand sets: the C++ kernel and the Python fallback must
        launch the same node counts."""
        import random

        from ray_tpu._native import get_native_lib

        if get_native_lib() is None:
            pytest.skip("native toolchain unavailable")
        rng = random.Random(7)
        types = {
            "small": {"resources": {"CPU": 2}, "max_workers": 5},
            "big": {"resources": {"CPU": 8, "TPU": 4}, "max_workers": 3},
        }
        for trial in range(25):
            demands = [
                _w({"CPU": rng.choice([1, 2, 4]),
                    **({"TPU": rng.choice([1, 2])} if rng.random() < 0.3
                       else {})})
                for _ in range(rng.randint(0, 6))
            ]
            pools = [_w({"CPU": rng.choice([0, 2, 4])})
                     for _ in range(rng.randint(0, 2))]
            args = (types, list(demands), list(pools), {}, 6, 0)
            monkeypatch.setenv("RAY_TPU_NATIVE_SCHED", "1")
            native = get_nodes_to_launch(*args)
            monkeypatch.setenv("RAY_TPU_NATIVE_SCHED", "0")
            python = get_nodes_to_launch(*args)
            assert sum(native.values()) == sum(python.values()), \
                (trial, demands, pools, native, python)

    def test_review_repro_native_python_agree(self, monkeypatch):
        """Regression: mixed demand sizes + partial pool previously made the
        two paths disagree ({'small': 2} vs {'big': 1})."""
        from ray_tpu._native import get_native_lib

        if get_native_lib() is None:
            pytest.skip("native toolchain unavailable")
        types = {
            "small": {"resources": {"CPU": 2}, "max_workers": 5},
            "big": {"resources": {"CPU": 8, "TPU": 4}, "max_workers": 3},
        }
        demands = [_w({"CPU": 2}), _w({"CPU": 2}), _w({"CPU": 4})]
        pools = [_w({"CPU": 4})]
        monkeypatch.setenv("RAY_TPU_NATIVE_SCHED", "1")
        native = get_nodes_to_launch(types, list(demands), list(pools), {}, 6, 0)
        monkeypatch.setenv("RAY_TPU_NATIVE_SCHED", "0")
        python = get_nodes_to_launch(types, list(demands), list(pools), {}, 6, 0)
        assert native == python, (native, python)
