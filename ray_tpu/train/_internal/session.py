"""Worker-side training session (reference:
python/ray/train/_internal/session.py — report :394/:654, world-rank
accessors). One ``_TrainSession`` lives per train-worker process; the user
loop talks to it through ``ray_tpu.train.report`` / ``get_context``."""

from __future__ import annotations

import os
import queue
import shutil
import threading
from typing import Any, Dict, Optional

from ray_tpu.train._checkpoint import Checkpoint

_session_lock = threading.Lock()
_session: Optional["_TrainSession"] = None


class TrainingResult:
    REPORT = "report"
    DONE = "done"
    ERROR = "error"

    def __init__(self, kind: str, metrics: Optional[Dict] = None,
                 checkpoint_dir: Optional[str] = None,
                 error: Optional[str] = None):
        self.kind = kind
        self.metrics = metrics or {}
        self.checkpoint_dir = checkpoint_dir
        self.error = error

    def to_wire(self) -> Dict:
        return {"kind": self.kind, "metrics": self.metrics,
                "checkpoint_dir": self.checkpoint_dir, "error": self.error}

    @classmethod
    def from_wire(cls, d: Dict) -> "TrainingResult":
        return cls(d["kind"], d.get("metrics"), d.get("checkpoint_dir"),
                   d.get("error"))


class _TrainSession:
    def __init__(self, world_rank: int, world_size: int, local_rank: int,
                 local_world_size: int, node_rank: int,
                 experiment_name: str, storage_path: str,
                 trial_dir: str, config: Dict,
                 checkpoint: Optional[Checkpoint] = None,
                 dataset_shards: Optional[Dict[str, Any]] = None):
        self.world_rank = world_rank
        self.world_size = world_size
        self.local_rank = local_rank
        self.local_world_size = local_world_size
        self.node_rank = node_rank
        self.experiment_name = experiment_name
        self.storage_path = storage_path
        self.trial_dir = trial_dir
        self.config = config
        self.loaded_checkpoint = checkpoint
        self.dataset_shards = dataset_shards or {}
        self.result_queue: "queue.Queue[TrainingResult]" = queue.Queue()
        self.iteration = 0

    def report(self, metrics: Dict, checkpoint: Optional[Checkpoint] = None):
        ckpt_dir = None
        if checkpoint is not None:
            # Persist into the trial dir (StorageContext analog: reference
            # train/_internal/storage.py:99-111). Only rank 0 uploads in
            # the common fully-replicated case; other ranks may still pass
            # shard checkpoints which land in per-rank subdirs. When the
            # trial dir is a remote URI, THIS worker process uploads its
            # own shards directly (upload-from-worker: on a pod each host
            # pushes to the bucket; nothing round-trips the driver).
            from ray_tpu._private.storage import (
                get_storage_backend, is_remote_uri, join_uri)

            name = f"checkpoint_{self.iteration:06d}"
            if is_remote_uri(self.trial_dir):
                sub = [] if self.world_rank == 0 \
                    else [f"rank_{self.world_rank}"]
                dest = join_uri(self.trial_dir, name, *sub)
                get_storage_backend(dest).upload_dir(checkpoint.path, dest)
                ckpt_dir = join_uri(self.trial_dir, name)
            else:
                if self.world_rank == 0:
                    dest = os.path.join(self.trial_dir, name)
                else:
                    dest = os.path.join(self.trial_dir, name,
                                        f"rank_{self.world_rank}")
                os.makedirs(os.path.dirname(dest), exist_ok=True)
                if os.path.abspath(checkpoint.path) != os.path.abspath(dest):
                    shutil.copytree(checkpoint.path, dest,
                                    dirs_exist_ok=True)
                ckpt_dir = os.path.join(self.trial_dir, name)
        self.iteration += 1
        self.result_queue.put(
            TrainingResult(TrainingResult.REPORT, metrics, ckpt_dir))

    def get_checkpoint(self) -> Optional[Checkpoint]:
        return self.loaded_checkpoint

    def get_dataset_shard(self, name: str = "train"):
        shard = self.dataset_shards.get(name)
        if shard is None:
            raise KeyError(f"no dataset shard named {name!r}")
        return shard


class TrainContext:
    """What ``ray_tpu.train.get_context()`` returns inside a worker
    (reference: ray.train.get_context TrainContext)."""

    def get_world_rank(self) -> int:
        return get_session().world_rank

    def get_world_size(self) -> int:
        return get_session().world_size

    def get_local_rank(self) -> int:
        return get_session().local_rank

    def get_local_world_size(self) -> int:
        return get_session().local_world_size

    def get_node_rank(self) -> int:
        return get_session().node_rank

    def get_experiment_name(self) -> str:
        return get_session().experiment_name

    def get_trial_dir(self) -> str:
        return get_session().trial_dir

    def get_storage(self):
        return get_session().storage_path


def init_session(**kwargs) -> _TrainSession:
    global _session
    with _session_lock:
        _session = _TrainSession(**kwargs)
        return _session


def get_session() -> _TrainSession:
    if _session is None:
        raise RuntimeError(
            "Not inside a ray_tpu.train session — this API must be called "
            "from within train_loop_per_worker")
    return _session


def shutdown_session() -> None:
    global _session
    with _session_lock:
        _session = None


def in_session() -> bool:
    return _session is not None
