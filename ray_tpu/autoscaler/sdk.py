"""Programmatic autoscaler requests (reference:
python/ray/autoscaler/sdk.py ``request_resources``) — pins a minimum demand
the autoscaler must satisfy regardless of queued tasks."""

from __future__ import annotations

import json
from typing import Dict, List, Optional

REQUEST_RESOURCES_KEY = "__request_resources"


def request_resources(num_cpus: Optional[int] = None,
                      bundles: Optional[List[Dict[str, float]]] = None) -> None:
    """Ask the autoscaler to scale to accommodate the given demand
    immediately; persists until the next call overrides it."""
    import ray_tpu

    entries: List[Dict[str, float]] = []
    if num_cpus:
        entries.append({"CPU": num_cpus})
    if bundles:
        entries.extend(bundles)
    from ray_tpu._private.config import CONFIG
    from ray_tpu._private.resources import ResourceSet

    wire = [ResourceSet(e).to_wire() for e in entries]
    w = ray_tpu._private.worker.global_worker
    w._acall(w.head.call("KvPut", {
        "ns": "autoscaler", "key": REQUEST_RESOURCES_KEY,
        "value": json.dumps(wire), "overwrite": True},
        timeout=CONFIG.control_rpc_timeout_s))
