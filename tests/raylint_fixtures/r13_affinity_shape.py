"""R13 regression fixture: cross-domain plain attribute mutation.

The shipped shape (PR 18): the completion queue's ``_completion_buf``/
``_completions_armed`` are appended by RPC read-loop code and
read-modify-written by the drain path — the hand-off is correct only
because every touch is marshalled onto the event loop; PR 12's shm
feeder thread had the same pattern against the loop. R13 pins that
discipline: a ``self.<attr>`` plainly mutated from two affinity domains
(loop / executor thread / GC) with no lock in scope is flagged at every
unguarded site.

Shapes below:

- ``ProgressShape`` — ``_rows`` bumped by an ``async def`` handler
  (loop domain) and zeroed by a ``threading.Thread`` drainer (thread
  domain), no hand-off: both sites flag.
- ``FinalizerShape`` — ``_handle`` nulled from a loop callback and from
  ``__del__`` (GC domain): both sites flag.
- ``GuardedProgressShape`` — the lock fix: same two domains, every
  mutation under the shared lock, no flag.
- ``SingleDomainShape`` — loop-confinement (the other valid
  discipline): all mutation on the loop, no flag.
- ``CtorPlusLoopShape`` — ``__init__`` writes happen-before
  publication and are exempt; one runtime domain remains, no flag.
"""

import threading


class ProgressShape:
    """The bug: loop handler and drainer thread race on ``_rows``."""

    def __init__(self):
        self._rows = 0
        self._thread = threading.Thread(target=self._drain, daemon=True)

    async def on_frame(self, n):
        self._rows += n  # expect-R13

    def _drain(self):
        self._rows = 0  # expect-R13


class FinalizerShape:
    """The GC variant: a destructor races the loop-side reset."""

    def __init__(self):
        self._handle = object()

    async def reset(self):
        self._handle = None  # expect-R13

    def __del__(self):
        self._handle = None  # expect-R13


class GuardedProgressShape:
    """The fix: both domains mutate under the shared lock — no flag."""

    def __init__(self):
        self._lock = threading.Lock()
        self._rows = 0
        self._thread = threading.Thread(target=self._drain, daemon=True)

    async def on_frame(self, n):
        with self._lock:
            self._rows += n

    def _drain(self):
        with self._lock:
            self._rows = 0


class SingleDomainShape:
    """Loop-confined: every mutation runs on the event loop — no flag."""

    def __init__(self):
        self._pending = []

    async def enqueue(self, item):
        self._pending = self._pending + [item]

    async def reset(self):
        self._pending = []


class CtorPlusLoopShape:
    """Construction happens-before publication: the ``__init__`` write
    does not count as a second domain — no flag."""

    def __init__(self):
        self._state = "new"

    async def activate(self):
        self._state = "active"
