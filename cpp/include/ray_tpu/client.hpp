// ray_tpu C++ driver API (reference: cpp/include/ray/api.h — the
// reference's C++ worker links the full core_worker; this client speaks
// the framework's own msgpack control plane directly: head RPCs for
// KV/cluster state, agent RPCs for worker leases, and direct PushTask to
// leased workers with cross-language specs executed by Python workers).
//
// Scope (documented in cpp/README.md): a native DRIVER — connect, KV,
// cluster view, and SubmitPyTask (lease → push → msgpack result). C++
// task *execution* (registering C++ functions as workers) is not
// implemented; tasks target Python functions by "module:qualname".

#pragma once

#include <cstdint>
#include <memory>
#include <string>
#include <vector>

#include "ray_tpu/msgpack.hpp"

namespace ray_tpu {

// One length-prefixed-frame RPC connection (protocol.py:
//   <u32 LE length><msgpack {"m", "i", "p"}>  →  {"r": id, "p"|"e": ...}).
class RpcClient {
 public:
  RpcClient() = default;
  ~RpcClient();
  RpcClient(const RpcClient&) = delete;
  RpcClient& operator=(const RpcClient&) = delete;

  void Connect(const std::string& host, int port, double timeout_s = 10.0);
  bool connected() const { return fd_ >= 0; }
  int fd() const { return fd_; }
  void Close();

  // Synchronous call: sends the request and reads frames until the
  // matching reply arrives (server pushes are skipped). Throws
  // std::runtime_error on transport failure or an {"e": ...} reply.
  msgpack::Value Call(const std::string& method,
                      const msgpack::Value& payload);

 private:
  int fd_ = -1;
  uint32_t next_id_ = 1;
  std::string inbuf_;

  void send_all(const std::string& data);
  std::string read_frame();
};

struct TaskOptions {
  double num_cpus = 1.0;
  int num_returns = 1;
  std::string function_name_for_logs;  // defaults to the func ref
};

class RayClient {
 public:
  // Connects to a running cluster's head (host:port printed by
  // `ray_tpu start --head` / available from python as
  // ray_tpu._global_node.head_port).
  void Connect(const std::string& head_host, int head_port);

  // Internal KV (head GcsInternalKVManager analog).
  bool KvPut(const std::string& key, const std::string& value,
             bool overwrite = true, const std::string& ns = "default");
  // Returns nil Value when the key is absent.
  msgpack::Value KvGet(const std::string& key,
                       const std::string& ns = "default");

  // {node_id: {addr: {host, port}, alive, ...}, ...}
  msgpack::Value ClusterView();

  // Submit one task executed by a Python worker: func_ref is
  // "module:qualname" importable on the worker; args/kwargs are plain
  // msgpack values (cross-language arg kind "x"). Blocks until the
  // result; returns the unpacked return value. Throws with the remote
  // error message on task failure.
  msgpack::Value SubmitPyTask(const std::string& func_ref,
                              const std::vector<msgpack::Value>& args,
                              const TaskOptions& opts = {});

 private:
  RpcClient head_;
  std::string job_id_;
  uint64_t task_counter_ = 0;

  // agent connections are cached per (host, port)
  struct AgentConn {
    std::string host;
    int port;
    std::unique_ptr<RpcClient> client;
  };
  std::vector<AgentConn> agents_;

  RpcClient& AgentAt(const std::string& host, int port);
};

}  // namespace ray_tpu
