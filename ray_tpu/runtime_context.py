"""Runtime context introspection.

Parity with the reference (reference: ``python/ray/runtime_context.py``):
``get_runtime_context()`` exposes job/node/worker/task/actor identity and
assigned accelerator ids from inside any task or actor.
"""

from __future__ import annotations

import os
from typing import Dict, List, Optional

from ray_tpu._private import worker as worker_mod


class RuntimeContext:
    def __init__(self, worker):
        self._worker = worker

    def get_job_id(self) -> str:
        return self._worker.job_id.hex()

    def get_node_id(self) -> str:
        return self._worker.node_id

    def get_worker_id(self) -> str:
        return self._worker.worker_id.hex()

    def get_task_id(self) -> Optional[str]:
        tid = getattr(self._worker.current_task_info, "task_id", None)
        return tid.hex() if tid else None

    def get_task_name(self) -> Optional[str]:
        return getattr(self._worker.current_task_info, "task_name", None)

    def get_actor_id(self) -> Optional[str]:
        actor_id = getattr(self._worker, "current_actor_id", None)
        return actor_id.hex() if actor_id else None

    def get_accelerator_ids(self) -> Dict[str, List[str]]:
        out: Dict[str, List[str]] = {}
        tpu = os.environ.get("TPU_VISIBLE_CHIPS")
        if tpu:
            out["TPU"] = tpu.split(",")
        gpu = os.environ.get("CUDA_VISIBLE_DEVICES")
        if gpu:
            out["GPU"] = gpu.split(",")
        return out

    @property
    def was_current_actor_reconstructed(self) -> bool:
        return False

    def get_assigned_resources(self) -> Dict[str, float]:
        return {}


def get_runtime_context() -> RuntimeContext:
    w = worker_mod.global_worker
    if w is None:
        raise RuntimeError("ray_tpu.init() has not been called")
    return RuntimeContext(w)
