"""P2P payload plane for the collective API (VERDICT r4 #5): bulk
tensors cross between members through the owner service/object plane
(ObjectRefs over the rendezvous store, bytes worker<->worker); the store
relays only metadata. Correctness at 100 MB across 4 member actors, and
the object path beats forced store-relay ≥2x (reference:
nccl_collective_group.py:127 p2p semantics, gloo_collective_group.py)."""

import os
import time

import numpy as np
import pytest

import ray_tpu
from ray_tpu.util import collective as col


@pytest.fixture(scope="module")
def ray8():
    if not ray_tpu.is_initialized():
        ray_tpu.init(num_cpus=8)
    yield
    ray_tpu.shutdown()


@ray_tpu.remote
class BulkMember:
    def __init__(self, rank, world_size, group, inline_max=None):
        if inline_max is not None:
            # env is authoritative on every CONFIG read — lets the bench
            # force the store-relay path in this member's process
            os.environ["RAY_TPU_COLLECTIVE_INLINE_MAX_BYTES"] = \
                str(inline_max)
        self.rank = rank
        self.ws = world_size
        self.group = group
        col.init_collective_group(world_size, rank, backend="cpu",
                                  group_name=group)

    def allreduce_mb(self, mbytes: int, check: bool = True):
        n = mbytes * 1024 * 1024 // 4
        x = np.full((n,), float(self.rank + 1), np.float32)
        t0 = time.perf_counter()
        out = col.allreduce(x, group_name=self.group)
        dt = time.perf_counter() - t0
        if check:
            want = float(self.ws * (self.ws + 1) / 2)
            assert out.shape == (n,), out.shape
            assert float(out[0]) == want and float(out[-1]) == want, (
                out[0], out[-1], want)
        return dt

    def sendrecv_mb(self, mbytes: int):
        n = mbytes * 1024 * 1024 // 4
        if self.rank == 0:
            col.send(np.full((n,), 7.0, np.float32), dst_rank=1,
                     group_name=self.group)
            return True
        out = col.recv(np.empty((n,), np.float32), src_rank=0,
                       group_name=self.group)
        return bool(out[0] == 7.0 and out[-1] == 7.0)


def test_100mb_allreduce_4_members(ray8):
    ms = [BulkMember.remote(r, 4, "bulk100") for r in range(4)]
    times = ray_tpu.get(
        [m.allreduce_mb.remote(100) for m in ms], timeout=600)
    assert len(times) == 4
    # and a bulk p2p send/recv through the same plane
    ms2 = [BulkMember.remote(r, 2, "bulkp2p") for r in range(2)]
    ok = ray_tpu.get([m.sendrecv_mb.remote(32) for m in ms2], timeout=300)
    assert ok[1] is True
    for m in ms + ms2:
        ray_tpu.kill(m)


def test_object_plane_beats_store_relay(ray8):
    """The point of the split: the store must not relay O(members x
    bytes). Forced-inline members funnel every byte through the
    rendezvous actor; default members move bytes via the object plane."""
    mb = 24

    def best_of_2(members):
        times = []
        for _ in range(2):
            times.append(max(ray_tpu.get(
                [m.allreduce_mb.remote(mb, False) for m in members],
                timeout=600)))
        return min(times)  # best-of-N damps shared-box noise

    relay = [BulkMember.remote(r, 4, "relay", inline_max=1 << 40)
             for r in range(4)]
    ray_tpu.get([m.allreduce_mb.remote(1, False) for m in relay],
                timeout=300)  # warm
    t_relay = best_of_2(relay)

    plane = [BulkMember.remote(r, 4, "plane") for r in range(4)]
    ray_tpu.get([m.allreduce_mb.remote(1, False) for m in plane],
                timeout=300)  # warm
    t_plane = best_of_2(plane)

    for m in relay + plane:
        ray_tpu.kill(m)
    assert t_plane * 2 <= t_relay, (
        f"object plane {t_plane:.2f}s not ≥2x faster than "
        f"store relay {t_relay:.2f}s")
