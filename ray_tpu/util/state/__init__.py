"""State API (reference: python/ray/util/state/api.py — list_actors :782,
list_tasks :1014, summaries :1376; aggregated by
dashboard/state_aggregator.py StateAPIManager :141).

Queries go to the head's info handlers; per-worker live state rides the
task-event store the way the reference pairs GCS data with
``QueryAllWorkerStates``.
"""

from __future__ import annotations

import collections
from typing import Any, Dict, List, Optional

__all__ = [
    "list_actors", "list_nodes", "list_tasks", "list_placement_groups",
    "list_jobs", "summarize_tasks", "summarize_actors",
]


def _worker():
    from ray_tpu._private import worker as worker_mod

    w = worker_mod.global_worker
    if w is None or not w.connected:
        raise RuntimeError("ray_tpu.init() must be called first")
    return w


def _call(method: str, payload: Optional[Dict] = None):
    w = _worker()
    return w._acall(w.head.call(method, payload or {}))


def _apply_filters(rows: List[Dict], filters) -> List[Dict]:
    """filters: [(key, op, value)] with op in ('=', '!=')."""
    for key, op, value in filters or []:
        if op == "=":
            rows = [r for r in rows if str(r.get(key)) == str(value)]
        elif op == "!=":
            rows = [r for r in rows if str(r.get(key)) != str(value)]
        else:
            raise ValueError(f"unsupported filter op {op!r}")
    return rows


def list_actors(filters=None, limit: int = 1000) -> List[Dict]:
    rows = _call("ListActors")
    return _apply_filters(rows, filters)[:limit]


def list_nodes(filters=None, limit: int = 1000) -> List[Dict]:
    rows = _call("ListNodes")
    for r in rows:
        r["state"] = "ALIVE" if r.get("alive") else "DEAD"
    return _apply_filters(rows, filters)[:limit]


def list_tasks(filters=None, limit: int = 10000) -> List[Dict]:
    w = _worker()
    w.flush_task_events()
    rows = _call("ListTaskEvents", {"limit": limit * 4})
    return _apply_filters(rows, filters)[:limit]


def list_placement_groups(filters=None, limit: int = 1000) -> List[Dict]:
    rows = _call("ListPlacementGroups")
    return _apply_filters(rows, filters)[:limit]


def list_jobs(filters=None, limit: int = 1000) -> List[Dict]:
    rows = _call("ListJobs")
    return _apply_filters(rows, filters)[:limit]


def summarize_tasks() -> Dict[str, Dict]:
    """Per-function-name counts by state (reference: ``ray summary tasks``)."""
    by_name: Dict[str, collections.Counter] = collections.defaultdict(
        collections.Counter)
    for e in list_tasks():
        by_name[e.get("name", "?")][e.get("state", "?")] += 1
    return {name: dict(states) for name, states in by_name.items()}


def summarize_actors() -> Dict[str, Dict]:
    by_class: Dict[str, collections.Counter] = collections.defaultdict(
        collections.Counter)
    for a in list_actors():
        by_class[a.get("class_name", "?")][a.get("state", "?")] += 1
    return {cls: dict(states) for cls, states in by_class.items()}
