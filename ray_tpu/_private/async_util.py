"""Tracked background tasks (the raylint R4 contract).

``asyncio``'s event loop holds only a *weak* reference to a task: a
``create_task`` whose handle nobody retains can be garbage-collected
mid-flight ("Task was destroyed but it is pending!" — the PRs 1/3 leak
class), and an exception raised inside it is never observed — the daemon
it implemented is silently gone (the pre-PR 5 GCS-loop failure mode).

``spawn_tracked``/``hold_task`` give fire-and-forget call sites the two
missing guarantees with one line: the handle is pinned in a module-level
registry until done, and a crash is logged with its traceback. The GCS
keeps its own ``_hold_task`` (its supervisor also *restarts* loops);
everything else uses this.
"""

from __future__ import annotations

import asyncio
import logging
import os
from typing import Coroutine, Optional, Set

logger = logging.getLogger("ray_tpu")

# The FAST tier runs every process under PYTHONASYNCIODEBUG (conftest
# hardening, ISSUE 7). Debug mode's per-step "Executing <Task ...> took
# Ns" WARNINGs fire constantly on a starved 1-core CI box (every jax
# compile beats the 100 ms slow-callback threshold) and the daemons'
# copies stream back through the log monitor into driver stdout,
# corrupting pytest's progress output. Keep the valuable debug checks
# (never-awaited origins, cross-thread call_soon raising, task creation
# tracebacks) but mute the asyncio logger to ERROR — hard failures like
# "Task exception was never retrieved" still surface. Gated on the
# conftest-set marker (inherited by daemons), NOT on PYTHONASYNCIODEBUG
# alone: an application debugging its own loop with ray_tpu imported
# must keep the warnings it asked for. Opt back in with
# RAY_TPU_ASYNCIO_DEBUG_VERBOSE=1 when hunting a blocking call.
if (os.environ.get("RAY_TPU_ASYNCIO_DEBUG_QUIET") == "1"
        and os.environ.get("RAY_TPU_ASYNCIO_DEBUG_VERBOSE", "0") != "1"):
    logging.getLogger("asyncio").setLevel(logging.ERROR)

# strong refs until done; a module-level set so helpers on short-lived
# objects (connections, lease pools) don't need per-instance plumbing
_TRACKED: Set["asyncio.Task"] = set()

# dead-loop sweep high-water mark: hold_task is on the RPC server's
# per-message dispatch path, so the O(len(_TRACKED)) reap must be
# amortized — sweep only when the set outgrows this, then re-arm at 2x
# the survivors. Dead entries linger below the floor, but bounded (<64),
# never the one-graph-per-init/shutdown-cycle growth the sweep exists for.
_SWEEP_FLOOR = 64
_sweep_at = _SWEEP_FLOOR


def _reap_dead_loops() -> None:
    """Drop tracked tasks whose done-callback can never run.

    The callback is delivered via ``call_soon``; a task that completes in
    the same loop iteration that stops its loop (e.g. a disconnect drain
    ending in ``loop.stop()``), or a pending task whose loop stopped
    under it, keeps its _TRACKED entry forever — one leaked Worker/client
    graph per init/shutdown cycle. Swept from hold_task past the
    high-water mark; crashes are still logged.
    """
    for t in list(_TRACKED):
        try:
            loop = t.get_loop()
            if loop.is_running():
                continue  # live loop: the done-callback will deliver
            _TRACKED.discard(t)
            # a PENDING task on a stopped loop is dropped too: no loop
            # here ever restarts, so it can never complete and would pin
            # its graph (and ratchet _sweep_at) forever
            if t.done() and not t.cancelled():
                exc = t.exception()
                if exc is not None:
                    logger.error("background task crashed (loop "
                                 "stopped): %r", exc, exc_info=exc)
        except Exception:
            _TRACKED.discard(t)


def hold_task(task: "asyncio.Task", tag: str = "") -> "asyncio.Task":
    """Pin ``task`` until completion and log a crash instead of losing it.

    Cancellation is a normal shutdown path and is not logged.
    """
    global _sweep_at
    if len(_TRACKED) >= _sweep_at:
        _reap_dead_loops()
        _sweep_at = max(_SWEEP_FLOOR, 2 * len(_TRACKED))
    _TRACKED.add(task)

    def _done(t: "asyncio.Task", _tag: str = tag) -> None:
        _TRACKED.discard(t)
        if t.cancelled():
            return
        exc = t.exception()  # marks the exception retrieved
        if exc is not None:
            logger.error("background task%s crashed: %r",
                         f" [{_tag}]" if _tag else "", exc, exc_info=exc)

    task.add_done_callback(_done)
    return task


def spawn_tracked(coro: Coroutine, tag: str = "",
                  loop: Optional["asyncio.AbstractEventLoop"] = None
                  ) -> "asyncio.Task":
    """``create_task`` + ``hold_task`` in one call (running-loop context
    unless ``loop`` is given, which must be the running loop)."""
    if loop is None:
        loop = asyncio.get_running_loop()
    return hold_task(loop.create_task(coro), tag)


def tracked_count() -> int:
    """Currently-live tracked tasks (leak-gate introspection)."""
    return len(_TRACKED)


class DecorrelatedJitterBackoff:
    """Decorrelated-jitter reconnect pacing (AWS architecture-blog
    "exponential backoff and jitter", the ``decorrelated`` variant):
    ``sleep = min(cap, uniform(base, prev * 3))``.

    The head watchdogs previously used a FIXED doubling schedule — after
    a head bounce, every agent and driver in the cluster woke on the
    same 0.2/0.4/0.8… grid and re-registered in synchronized waves (a
    thundering herd exactly when the freshly restarted head is busiest
    replaying its WAL). Decorrelation spreads each client's retries
    across the whole interval while keeping the expected pace
    exponential.
    """

    def __init__(self, base_s: float = 0.2, cap_s: float = 2.0, rng=None):
        import random

        if base_s <= 0:
            raise ValueError("base_s must be positive")
        self.base_s = float(base_s)
        self.cap_s = max(float(cap_s), self.base_s)
        self._rng = rng if rng is not None else random.Random()
        self._prev = self.base_s

    def next_delay(self) -> float:
        """The next sleep; grows (on average) until capped, never below
        base, never above cap, and never the same sequence twice."""
        self._prev = min(self.cap_s,
                         self._rng.uniform(self.base_s, self._prev * 3))
        return self._prev

    def reset(self) -> None:
        """Back to base pacing after a successful (re)connect."""
        self._prev = self.base_s
