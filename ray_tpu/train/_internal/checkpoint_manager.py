"""Top-K checkpoint retention (reference:
python/ray/train/_internal/checkpoint_manager.py, config
air/config.py:427)."""

from __future__ import annotations

import shutil
from typing import Dict, List, Optional, Tuple

from ray_tpu.air.config import CheckpointConfig
from ray_tpu.train._checkpoint import Checkpoint


class _TrackedCheckpoint:
    def __init__(self, checkpoint: Checkpoint, metrics: Dict, index: int):
        self.checkpoint = checkpoint
        self.metrics = metrics
        self.index = index


class _InStoreManifest:
    """One in-store sharded checkpoint: {world_rank: driver-owned ref}."""

    def __init__(self, step: int, world_size: int, shards: Dict,
                 metrics: Dict, nbytes: int):
        self.step = step
        self.world_size = world_size
        self.shards = shards  # {int rank: ObjectRef}
        self.metrics = metrics
        self.nbytes = nbytes

    def to_wire(self) -> Dict:
        return {"step": self.step, "world_size": self.world_size,
                "shards": dict(self.shards)}


class CheckpointManager:
    def __init__(self, config: Optional[CheckpointConfig] = None):
        self.config = config or CheckpointConfig()
        self._checkpoints: List[_TrackedCheckpoint] = []
        self._counter = 0
        self._in_store: List[_InStoreManifest] = []

    def register_checkpoint(self, checkpoint: Checkpoint, metrics: Dict) -> None:
        self._counter += 1
        self._checkpoints.append(
            _TrackedCheckpoint(checkpoint, metrics, self._counter))
        keep = self.config.num_to_keep
        if keep is None or len(self._checkpoints) <= keep:
            return
        attr = self.config.checkpoint_score_attribute
        if attr:
            ranked = sorted(self._checkpoints, key=self._score, reverse=True)
        else:
            ranked = sorted(self._checkpoints, key=lambda t: t.index,
                            reverse=True)
        for dropped in ranked[keep:]:
            self._checkpoints.remove(dropped)
            # scheme-aware: remote checkpoints are deleted through their
            # storage backend, local ones from disk; a failed remote delete
            # must be loud (a silently-leaked bucket prefix grows forever)
            from ray_tpu._private.storage import (
                get_storage_backend, is_remote_uri)

            if is_remote_uri(dropped.checkpoint.path):
                try:
                    get_storage_backend(dropped.checkpoint.path).delete(
                        dropped.checkpoint.path)
                except Exception as e:
                    import logging

                    logging.getLogger(__name__).warning(
                        "failed to prune remote checkpoint %s: %s",
                        dropped.checkpoint.path, e)
            else:
                shutil.rmtree(dropped.checkpoint.path, ignore_errors=True)

    # ------------------------------------------------- in-store manifests
    def register_in_store(self, step: int, shards: Dict, metrics: Dict
                          ) -> bool:
        """Register one sharded in-store checkpoint.

        ``shards`` maps world_rank -> the worker-put ObjectRef of that
        rank's packed state. Worker-owned objects die with their owner —
        exactly the process the elastic path expects to lose — so the
        driver RE-OWNS each shard here (get the zero-copy view, put a
        driver-owned copy, pin it against eviction for the retention
        window). One get+put per shard per report; restore never touches
        disk.

        A worker can die BETWEEN reporting step N and the driver landing
        here — then its shard's ownership record is already gone. That is
        not a failure of the training round (the death will surface as a
        typed error on the next result round): abandon this step's
        manifest, keep the previous one, return False.
        """
        import ray_tpu
        from ray_tpu._private.config import CONFIG

        owned: Dict[int, object] = {}
        nbytes = 0
        for rank, ref in sorted(shards.items()):
            try:
                data = ray_tpu.get(ref)
                mine = ray_tpu.put(data)
            except Exception:
                for kept in owned.values():
                    self._unpin(kept)
                return False
            self._pin(mine)
            owned[int(rank)] = mine
            try:
                nbytes += len(memoryview(data).cast("B"))
            except TypeError:
                pass
        self._in_store.append(_InStoreManifest(
            int(step), len(owned), owned, dict(metrics or {}), nbytes))
        keep = max(1, int(CONFIG.train_in_store_keep))
        while len(self._in_store) > keep:
            dropped = self._in_store.pop(0)
            for ref in dropped.shards.values():
                self._retire(ref)
        return True

    @staticmethod
    def _pin(ref) -> None:
        from ray_tpu._private.worker import global_worker

        try:
            global_worker.store.pin(ref.hex())
        except Exception:
            # inline objects live in the memory store; nothing to pin
            pass

    @staticmethod
    def _unpin(ref) -> None:
        from ray_tpu._private.worker import global_worker

        try:
            global_worker.store.unpin(ref.hex())
        except Exception:
            pass

    @classmethod
    def _retire(cls, ref) -> None:
        """A retired shard is never restored from again, and its only
        borrowers are train workers (possibly SIGKILLed ones whose
        RemoveBorrow can never arrive) — unpin AND force-clear stale
        borrows so the driver-owned bytes actually free."""
        from ray_tpu._private.worker import global_worker

        cls._unpin(ref)
        try:
            global_worker.reference_counter.clear_borrows(ref.binary())
        except Exception:
            pass

    def latest_in_store_manifest(self) -> Optional[Dict]:
        """Wire form of the newest in-store checkpoint ({step, world_size,
        shards}) for ``init_train_session(checkpoint_shards=...)``."""
        if not self._in_store:
            return None
        return self._in_store[-1].to_wire()

    @property
    def latest_in_store_step(self) -> Optional[int]:
        return self._in_store[-1].step if self._in_store else None

    def release_in_store(self) -> None:
        """Retire every tracked shard (trainer exit)."""
        for m in self._in_store:
            for ref in m.shards.values():
                self._retire(ref)
        self._in_store = []

    def _score(self, t: _TrackedCheckpoint) -> Tuple:
        """Rank key, higher = better. A checkpoint missing the score
        attribute ranks worst in BOTH orders (leading bool), so min-order
        can't accidentally crown it via -1 * -inf."""
        attr = self.config.checkpoint_score_attribute
        sign = 1 if self.config.checkpoint_score_order == "max" else -1
        val = t.metrics.get(attr)
        return (val is not None, sign * val if val is not None else 0.0,
                t.index)

    @property
    def latest_checkpoint(self) -> Optional[Checkpoint]:
        if not self._checkpoints:
            return None
        return max(self._checkpoints, key=lambda t: t.index).checkpoint

    @property
    def best_checkpoint(self) -> Optional[Checkpoint]:
        if not self._checkpoints:
            return None
        attr = self.config.checkpoint_score_attribute
        if not attr:
            return self.latest_checkpoint
        return max(self._checkpoints, key=self._score).checkpoint

    def best_checkpoints(self) -> List[Tuple[Checkpoint, Dict]]:
        return [(t.checkpoint, t.metrics)
                for t in sorted(self._checkpoints, key=lambda t: t.index)]
