"""Unique identifiers for every entity in the system.

Behavioral parity with the reference's ID scheme (reference:
``src/ray/common/id.h``) — jobs, tasks, objects, actors, nodes and workers all
carry fixed-width binary ids with cheap hashing and hex round-tripping — but the
layout is our own: ids are plain ``bytes`` wrapped in small value classes, with
object ids derived from ``(task id, return index)`` so ownership and lineage can
be recovered from the id itself without a lookup table.
"""

from __future__ import annotations

import os
import struct
import threading

_NIL = b"\x00"

# Buffered entropy: a syscall per id (~80µs of urandom on a loaded box) is
# measurable in the submit hot loop; refill in 16 KiB chunks instead.
_rand_lock = threading.Lock()
_rand_buf = b""
_rand_off = 0


def _rand_bytes(n: int) -> bytes:
    global _rand_buf, _rand_off
    with _rand_lock:
        if _rand_off + n > len(_rand_buf):
            # a block request larger than the refill unit (submit_many id
            # blocks) gets a buffer sized to fit in one syscall
            _rand_buf = os.urandom(max(16384, n))
            _rand_off = 0
        out = _rand_buf[_rand_off:_rand_off + n]
        _rand_off += n
    return out


def _reset_rand_buffer() -> None:
    # fork safety: a child continuing from the parent's buffer offset would
    # mint identical ids
    global _rand_buf, _rand_off
    _rand_buf = b""
    _rand_off = 0


if hasattr(os, "register_at_fork"):
    os.register_at_fork(after_in_child=_reset_rand_buffer)


class BaseID:
    """A fixed-size binary id. Immutable, hashable, ordered."""

    SIZE = 16
    __slots__ = ("_bytes", "_hash")

    def __init__(self, id_bytes: bytes):
        if len(id_bytes) != self.SIZE:
            raise ValueError(
                f"{type(self).__name__} requires {self.SIZE} bytes, got {len(id_bytes)}"
            )
        self._bytes = id_bytes
        self._hash = hash(id_bytes)

    @classmethod
    def from_random(cls) -> "BaseID":
        return cls(_rand_bytes(cls.SIZE))

    @classmethod
    def random_block(cls, n: int) -> list:
        """n fresh ids minted from ONE entropy-buffer slice (one lock
        acquisition instead of n) — the id-allocation block behind
        ``submit_many``."""
        size = cls.SIZE
        buf = _rand_bytes(size * n)
        return [cls(buf[i * size:(i + 1) * size]) for i in range(n)]

    @classmethod
    def from_hex(cls, hex_str: str) -> "BaseID":
        return cls(bytes.fromhex(hex_str))

    @classmethod
    def nil(cls) -> "BaseID":
        return cls(_NIL * cls.SIZE)

    def is_nil(self) -> bool:
        return self._bytes == _NIL * self.SIZE

    def binary(self) -> bytes:
        return self._bytes

    def hex(self) -> str:
        return self._bytes.hex()

    def __hash__(self) -> int:
        return self._hash

    def __eq__(self, other) -> bool:
        return type(other) is type(self) and other._bytes == self._bytes

    def __lt__(self, other) -> bool:
        return self._bytes < other._bytes

    def __repr__(self) -> str:
        return f"{type(self).__name__}({self._bytes.hex()})"

    def __reduce__(self):
        return (type(self), (self._bytes,))


class JobID(BaseID):
    SIZE = 4


class NodeID(BaseID):
    SIZE = 16


class WorkerID(BaseID):
    SIZE = 16


class ActorID(BaseID):
    SIZE = 16


class PlacementGroupID(BaseID):
    SIZE = 16


class FunctionID(BaseID):
    SIZE = 16


class TaskID(BaseID):
    """16 random bytes. Actor-creation / actor tasks embed the actor id prefix so
    debugging tools can group them (same intent as reference id.h's structured
    task ids, different layout)."""

    SIZE = 16

    # (actor_id, caller_id) -> hashed prefix: constant per handle, and
    # for_actor_task sits on the actor-call hot path
    _prefix_cache: dict = {}

    @classmethod
    def for_actor_task(cls, actor_id: ActorID, seq: int,
                       caller_id: bytes = b"") -> "TaskID":
        # Mix caller identity in so two callers' seq counters can't collide
        # on the same task id (and hence the same return ObjectIDs).
        key = (actor_id.binary(), caller_id)
        prefix = cls._prefix_cache.get(key)
        if prefix is None:
            import hashlib

            prefix = hashlib.blake2b(
                actor_id.binary() + caller_id, digest_size=8).digest()
            if len(cls._prefix_cache) > 65536:  # unbounded-growth guard
                cls._prefix_cache.clear()
            cls._prefix_cache[key] = prefix
        return cls(prefix + struct.pack("<Q", seq))


class ObjectID(BaseID):
    """task id (16 bytes) + little-endian return index (4 bytes)."""

    SIZE = 20

    @classmethod
    def for_task_return(cls, task_id: TaskID, index: int) -> "ObjectID":
        return cls(task_id.binary() + struct.pack("<I", index))

    @classmethod
    def from_put(cls, worker_put_counter: int, worker_id: WorkerID) -> "ObjectID":
        # Puts get a synthetic "task id" derived from the worker id so the owner
        # is recoverable; high bit of the index marks it as a put.
        fake_task = worker_id.binary()[:12] + struct.pack("<I", 0xFFFFFFFF)
        return cls(fake_task + struct.pack("<I", worker_put_counter | 0x80000000))

    def task_id(self) -> TaskID:
        return TaskID(self._bytes[:16])

    def return_index(self) -> int:
        return struct.unpack("<I", self._bytes[16:20])[0] & 0x7FFFFFFF

    def is_put(self) -> bool:
        return bool(struct.unpack("<I", self._bytes[16:20])[0] & 0x80000000)


class _Counter:
    """Thread-safe monotonically increasing counter."""

    def __init__(self):
        self._value = 0
        self._lock = threading.Lock()

    def next(self) -> int:
        with self._lock:
            self._value += 1
            return self._value
