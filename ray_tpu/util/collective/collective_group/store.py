"""Named rendezvous store actor for host-side collectives.

Reference analog: python/ray/util/collective/collective_group/gloo_util.py:29-98
(the named-actor Store used for gloo rendezvous). The store carries
rendezvous state and INLINE payloads only for metadata-sized tensors;
bulk tensors cross as ObjectRefs whose bytes move worker<->worker through
the object plane (cpu_group._boxed), so this actor never relays
O(members x bytes). On a real multi-host TPU pod, bulk traffic rides ICI
inside the global XLA mesh and this store only ever sees group metadata.

All methods are non-blocking so a ``max_concurrency=1`` actor can serve every
member; callers poll.
"""

from __future__ import annotations

import time
from typing import Any, Dict, List, Optional


class CollectiveStore:
    """One instance per group, named ``_collective_store:{group_name}``."""

    def __init__(self):
        # op_key -> {rank: payload}
        self._parts: Dict[str, Dict[int, Any]] = {}
        # op_key -> number of members that already read the completed set
        self._reads: Dict[str, int] = {}
        # op_key -> number of members that finished fetching boxed refs
        self._confirms: Dict[str, int] = {}
        # op_key / p2p key -> creation time (orphan TTL sweeps)
        self._born: Dict[str, float] = {}
        self._p2p: Dict[str, Any] = {}
        self._members: Dict[int, float] = {}

    def register(self, rank: int) -> int:
        self._members[rank] = time.time()
        return len(self._members)

    def num_members(self) -> int:
        return len(self._members)

    def deregister(self, rank: int) -> int:
        self._members.pop(rank, None)
        return len(self._members)

    # entries older than this are orphans (a member died/timed out and
    # its confirms will never arrive): drop them so their ObjectRefs stop
    # pinning bulk payloads forever
    ORPHAN_TTL_S = 600.0

    def _sweep_orphans(self) -> None:
        now = time.time()
        for key, born in list(self._born.items()):
            if now - born > self.ORPHAN_TTL_S:
                self._parts.pop(key, None)
                self._reads.pop(key, None)
                self._confirms.pop(key, None)
                self._p2p.pop(key, None)
                del self._born[key]

    def contribute(self, op_key: str, rank: int, payload: Any) -> int:
        self._sweep_orphans()
        parts = self._parts.setdefault(op_key, {})
        self._born.setdefault(op_key, time.time())
        parts[rank] = payload
        return len(parts)

    def collect(self, op_key: str, world_size: int) -> Optional[List[Any]]:
        """Return payloads ordered by rank once all members contributed.

        Inline entries are garbage-collected after ``world_size``
        successful reads. Entries holding ObjectRefs (bulk payloads riding
        the object plane) are kept until every member ``confirm``s its
        fetch — this actor's copies are what pin the objects while slower
        members are still pulling the bytes.
        """
        parts = self._parts.get(op_key)
        if parts is None or len(parts) < world_size:
            return None
        out = [parts[r] for r in range(world_size)]
        boxed_refs = any(isinstance(p, tuple) and p and p[0] == "r"
                         for p in out)
        reads = self._reads.get(op_key, 0) + 1
        if reads >= world_size and not boxed_refs:
            del self._parts[op_key]
            self._reads.pop(op_key, None)
        else:
            self._reads[op_key] = reads
        return out

    def confirm(self, op_key: str, world_size: int) -> None:
        """A member finished FETCHING a boxed entry's payloads; the entry
        (and the refs pinning the objects) drops after the last one."""
        confirms = self._confirms.get(op_key, 0) + 1
        if confirms >= world_size:
            self._parts.pop(op_key, None)
            self._reads.pop(op_key, None)
            self._confirms.pop(op_key, None)
            self._born.pop(op_key, None)
        else:
            self._confirms[op_key] = confirms

    def put_p2p(self, key: str, payload: Any) -> None:
        self._sweep_orphans()
        self._p2p[key] = payload
        self._born.setdefault(key, time.time())

    def take_p2p(self, key: str) -> Optional[List[Any]]:
        """Boxed result ([payload] or None) so None payloads round-trip.

        Inline ("v") entries pop destructively — one round trip, the
        common metadata-sized path. Object-plane ("r") entries stay until
        confirm_p2p (their ref pins the payload while the receiver is
        still fetching the bytes)."""
        boxed = self._p2p.get(key)
        if boxed is None:
            return None
        if isinstance(boxed, tuple) and boxed and boxed[0] == "v":
            self._p2p.pop(key, None)
            self._born.pop(key, None)
        return [boxed]

    def confirm_p2p(self, key: str) -> None:
        self._p2p.pop(key, None)
        self._born.pop(key, None)

    def op_done(self, op_key: str) -> bool:
        """True once the entry is fully confirmed and dropped."""
        return op_key not in self._parts

    def p2p_absent(self, keys: List[str]) -> List[str]:
        """Which of these p2p entries are gone (receiver confirmed)."""
        return [k for k in keys if k not in self._p2p]
