from ray_tpu.rllib.algorithms.cql.cql import CQL, CQLConfig

__all__ = ["CQL", "CQLConfig"]
