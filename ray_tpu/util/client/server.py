"""Client-mode server: a real driver wrapped in an RPC facade
(reference: python/ray/util/client/server/server.py RayletServicer —
Terminate/GetObject/PutObject/Schedule RPCs over ray_client.proto)."""

from __future__ import annotations

import asyncio
import threading
from typing import Any, Dict, List, Optional

from ray_tpu._private import serialization as ser
from ray_tpu._private.async_util import hold_task
from ray_tpu._private.protocol import Connection, RpcServer


class ClientServer:
    """Runs inside (or next to) a real driver process; each client
    connection owns a namespace of refs/actors released on disconnect."""

    def __init__(self, host: str = "0.0.0.0", port: int = 10001):
        self.host = host
        self.port = port
        self.server = RpcServer("client-server")
        # per-connection state: id(conn) -> {"refs": {hex: ObjectRef},
        #                                    "actors": {hex: handle}}
        self._conns: Dict[int, Dict[str, Dict]] = {}
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        self._thread: Optional[threading.Thread] = None
        self._register_routes()

    # ------------------------------------------------------------ lifecycle
    def start(self) -> int:
        """Start serving on a daemon event-loop thread; returns the port."""
        ready = threading.Event()
        port_box = {}

        def run():
            loop = asyncio.new_event_loop()
            asyncio.set_event_loop(loop)
            self._loop = loop

            async def boot():
                port_box["port"] = await self.server.start_tcp(
                    self.host, self.port)
                self.server.set_disconnect_handler(self._on_disconnect)
                ready.set()

            hold_task(loop.create_task(boot()), "client-server-boot")
            loop.run_forever()

        self._thread = threading.Thread(
            target=run, name="ray-client-server", daemon=True)
        self._thread.start()
        if not ready.wait(30):
            raise TimeoutError("client server failed to start")
        self.port = port_box["port"]
        return self.port

    def stop(self) -> None:
        if self._loop:
            self._loop.call_soon_threadsafe(self._loop.stop)

    # -------------------------------------------------------------- routes
    def _register_routes(self) -> None:
        r = self.server.add_handler
        r("ClientInit", self._init)
        r("ClientPut", self._put)
        r("ClientGet", self._get)
        r("ClientTask", self._task)
        r("ClientCreateActor", self._create_actor)
        r("ClientActorCall", self._actor_call)
        r("ClientGetNamedActor", self._get_named_actor)
        r("ClientKill", self._kill)
        r("ClientCancel", self._cancel)
        r("ClientRelease", self._release)
        r("ClientWait", self._wait)
        r("ClientClusterInfo", self._cluster_info)

    def _state(self, conn: Connection) -> Dict[str, Dict]:
        return self._conns.setdefault(id(conn), {"refs": {}, "actors": {}})

    async def _on_disconnect(self, conn: Connection) -> None:
        state = self._conns.pop(id(conn), None)
        if state:
            state["refs"].clear()  # drops driver-side refs -> GC

    # ------------------------------------------------------------ handlers
    async def _init(self, conn: Connection, p: Dict) -> Dict:
        import ray_tpu

        if not ray_tpu.is_initialized():
            kwargs = ser.loads(bytes(p["init_kwargs"])) if p.get(
                "init_kwargs") else {}
            await asyncio.get_running_loop().run_in_executor(
                None, lambda: ray_tpu.init(**kwargs))
        return {"ok": True}

    def _track(self, conn: Connection, refs: List) -> List[Dict]:
        state = self._state(conn)
        out = []
        for ref in refs:
            state["refs"][ref.hex()] = ref
            out.append({"id": ref.hex()})
        return out

    async def _put(self, conn: Connection, p: Dict) -> Dict:
        import ray_tpu

        value = ser.loads(bytes(p["value"]))
        ref = await asyncio.get_running_loop().run_in_executor(
            None, ray_tpu.put, value)
        return {"refs": self._track(conn, [ref])}

    def _resolve_ref(self, conn: Connection, hex_id: str):
        ref = self._state(conn)["refs"].get(hex_id)
        if ref is None:
            raise ValueError(f"unknown client ref {hex_id}")
        return ref

    async def _get(self, conn: Connection, p: Dict) -> Dict:
        import ray_tpu

        refs = [self._resolve_ref(conn, h) for h in p["ids"]]
        timeout = p.get("timeout")

        def do_get():
            return ray_tpu.get(refs, timeout=timeout)

        try:
            values = await asyncio.get_running_loop().run_in_executor(
                None, do_get)
        except BaseException as e:  # noqa: BLE001 — shipped to the client
            return {"error": ser.dumps(e)}
        return {"values": [ser.dumps(v) for v in values]}

    def _materialize_args(self, conn: Connection, wire_args, wire_kwargs):
        args = [self._resolve_ref(conn, a["ref"]) if isinstance(a, dict)
                and "ref" in a else ser.loads(bytes(a["v"]))
                for a in wire_args]
        kwargs = {k: self._resolve_ref(conn, v["ref"]) if isinstance(v, dict)
                  and "ref" in v else ser.loads(bytes(v["v"]))
                  for k, v in (wire_kwargs or {}).items()}
        return args, kwargs

    async def _task(self, conn: Connection, p: Dict) -> Dict:
        import ray_tpu

        # runs in an executor: submission round-trips through the driver's
        # agent and must not stall other clients on this event loop
        def do_submit():
            fn = ser.loads(bytes(p["fn"]))
            opts = ser.loads(bytes(p["opts"])) if p.get("opts") else {}
            args, kwargs = self._materialize_args(conn, p["args"],
                                                  p.get("kwargs"))
            remote_fn = ray_tpu.remote(fn)
            if opts:
                remote_fn = remote_fn.options(**opts)
            out = remote_fn.remote(*args, **kwargs)
            return out if opts.get("num_returns", 1) != 1 else [out]

        refs = await asyncio.get_running_loop().run_in_executor(
            None, do_submit)
        return {"refs": self._track(conn, refs)}

    async def _create_actor(self, conn: Connection, p: Dict) -> Dict:
        import ray_tpu

        def do_create():
            cls = ser.loads(bytes(p["cls"]))
            opts = ser.loads(bytes(p["opts"])) if p.get("opts") else {}
            args, kwargs = self._materialize_args(conn, p["args"],
                                                  p.get("kwargs"))
            actor_cls = ray_tpu.remote(cls)
            if opts:
                actor_cls = actor_cls.options(**opts)
            return actor_cls.remote(*args, **kwargs)

        handle = await asyncio.get_running_loop().run_in_executor(
            None, do_create)
        hex_id = handle._actor_id.hex()
        self._state(conn)["actors"][hex_id] = handle
        return {"actor_id": hex_id}

    async def _get_named_actor(self, conn: Connection, p: Dict) -> Dict:
        import ray_tpu

        handle = await asyncio.get_running_loop().run_in_executor(
            None, lambda: ray_tpu.get_actor(
                p["name"], namespace=p.get("namespace") or "default"))
        hex_id = handle._actor_id.hex()
        self._state(conn)["actors"][hex_id] = handle
        return {"actor_id": hex_id}

    async def _actor_call(self, conn: Connection, p: Dict) -> Dict:
        handle = self._state(conn)["actors"].get(p["actor_id"])
        if handle is None:
            raise ValueError(f"unknown client actor {p['actor_id']}")

        def do_call():
            args, kwargs = self._materialize_args(conn, p["args"],
                                                  p.get("kwargs"))
            method = getattr(handle, p["method"])
            opts = ser.loads(bytes(p["opts"])) if p.get("opts") else {}
            if opts:
                method = method.options(**opts)
            out = method.remote(*args, **kwargs)
            return out if isinstance(out, list) else [out]

        refs = await asyncio.get_running_loop().run_in_executor(None, do_call)
        return {"refs": self._track(conn, refs)}

    async def _kill(self, conn: Connection, p: Dict) -> Dict:
        import ray_tpu

        handle = self._state(conn)["actors"].get(p["actor_id"])
        if handle is not None:
            await asyncio.get_running_loop().run_in_executor(
                None, lambda: ray_tpu.kill(
                    handle, no_restart=p.get("no_restart", True)))
        return {"ok": handle is not None}

    async def _cancel(self, conn: Connection, p: Dict) -> Dict:
        import ray_tpu

        ref = self._resolve_ref(conn, p["id"])
        await asyncio.get_running_loop().run_in_executor(
            None, lambda: ray_tpu.cancel(ref, force=p.get("force", False)))
        return {"ok": True}

    async def _release(self, conn: Connection, p: Dict) -> Dict:
        state = self._state(conn)
        for h in p["ids"]:
            state["refs"].pop(h, None)
        return {"ok": True}

    async def _wait(self, conn: Connection, p: Dict) -> Dict:
        import ray_tpu

        refs = [self._resolve_ref(conn, h) for h in p["ids"]]

        def do_wait():
            return ray_tpu.wait(refs, num_returns=p.get("num_returns", 1),
                                timeout=p.get("timeout"))

        ready, not_ready = await asyncio.get_running_loop().run_in_executor(
            None, do_wait)
        return {"ready": [r.hex() for r in ready],
                "not_ready": [r.hex() for r in not_ready]}

    async def _cluster_info(self, conn: Connection, p: Dict) -> Dict:
        import ray_tpu

        return {"nodes": ray_tpu.nodes(),
                "resources": ray_tpu.cluster_resources()}


def serve(host: str = "0.0.0.0", port: int = 10001) -> ClientServer:
    """Start a client server next to an already-initialized driver."""
    s = ClientServer(host, port)
    s.start()
    return s
