"""Dask-on-ray_tpu scheduler (reference: python/ray/util/dask/ —
ray_dask_get: a dask scheduler executing graph tasks as framework tasks).

Gated on `dask` being importable (not in this image's baked set). The
scheduler walks the dask graph in topological order, submitting each task
as a remote task whose arguments are the upstream ObjectRefs — dependency
resolution and scheduling then ride the framework's own object plane.
"""

from __future__ import annotations

from typing import Any, Dict

import ray_tpu


def _require_dask():
    try:
        import dask  # noqa: F401
    except ImportError as e:
        raise ImportError(
            "ray_tpu.util.dask requires `dask`, which is not installed "
            "in this environment.") from e


def ray_dask_get(dsk: Dict, keys, **kwargs) -> Any:
    """Drop-in dask scheduler: ``dask.compute(x, scheduler=ray_dask_get)``
    (reference: util/dask/scheduler.py ray_dask_get)."""
    _require_dask()
    import dask

    from dask.core import istask, toposort

    refs: Dict[Any, Any] = {}

    @ray_tpu.remote
    def run_task(func, *args):
        return func(*args)

    def _hashable(x):
        try:
            hash(x)
            return True
        except TypeError:
            return False

    def resolve(arg):
        """Swap graph keys for their (ref) results, recursing into
        collections AND nested task tuples the way dask graphs nest
        them — (add, (inc, 1), 2) executes inner tasks too."""
        if _hashable(arg) and arg in refs:
            return refs[arg]
        if istask(arg):
            return submit(arg)
        if isinstance(arg, list):
            return [resolve(a) for a in arg]
        if isinstance(arg, tuple):
            return tuple(resolve(a) for a in arg)
        return arg

    def submit(task_tuple):
        func, *args = task_tuple
        # refs pass straight through as task args: the runtime resolves
        # them to values before the function runs
        return run_task.remote(func, *[resolve(a) for a in args])

    for key in toposort(dsk):
        val = dsk[key]
        refs[key] = submit(val) if istask(val) else resolve(val)

    def fetch(k):
        v = refs[k]
        return ray_tpu.get(v) if isinstance(v, ray_tpu.ObjectRef) else v

    if isinstance(keys, list):
        return [fetch(k) if _hashable(k) and k in refs else k
                for k in keys]
    return fetch(keys)


def enable_dask_on_ray() -> None:
    """Set ray_dask_get as dask's default scheduler (reference:
    util/dask enable_dask_on_ray)."""
    _require_dask()
    import dask

    dask.config.set(scheduler=ray_dask_get)
