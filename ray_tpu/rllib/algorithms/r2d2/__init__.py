from ray_tpu.rllib.algorithms.r2d2.r2d2 import (  # noqa: F401
    R2D2, R2D2Config, R2D2Learner, R2D2Module, R2D2ModuleSpec)
