// Example C++ task worker: registers native functions and serves leases
// (reference: cpp/src/ray/runtime/task/task_executor.cc + the
// RAY_REMOTE-registered function table). Used by tests/test_cpp_client.py.
// Usage: example_worker <agent_host> <agent_tcp_port>

#include <cstdlib>
#include <iostream>

#include "ray_tpu/worker.hpp"

using ray_tpu::TaskWorker;
using ray_tpu::msgpack::Value;

namespace {

int64_t AsInt(const Value& v) {
  if (v.type == Value::Type::Int) return v.i;
  if (v.type == Value::Type::Double) return static_cast<int64_t>(v.d);
  throw std::runtime_error("expected an integer argument");
}

}  // namespace

int main(int argc, char** argv) {
  if (argc < 3) {
    std::cerr << "usage: example_worker <agent_host> <agent_tcp_port>\n";
    return 2;
  }
  TaskWorker w;
  w.Register("cpp.add", [](const std::vector<Value>& a) {
    int64_t s = 0;
    for (const Value& v : a) s += AsInt(v);
    return Value::Int(s);
  });
  w.Register("cpp.fib", [](const std::vector<Value>& a) {
    int64_t n = a.empty() ? 0 : AsInt(a[0]);
    int64_t x = 0, y = 1;
    for (int64_t i = 0; i < n; ++i) {
      int64_t t = x + y;
      x = y;
      y = t;
    }
    return Value::Int(x);
  });
  w.Register("cpp.echo", [](const std::vector<Value>& a) {
    return a.empty() ? Value::Nil() : a[0];
  });
  w.Register("cpp.fail", [](const std::vector<Value>&) -> Value {
    throw std::runtime_error("deliberate C++ failure");
  });
  std::cout << "cpp-worker starting\n" << std::flush;
  w.Serve(argv[1], std::atoi(argv[2]));
  return 0;
}
