"""HTTP proxy actor (reference: python/ray/serve/_private/proxy.py —
ProxyActor :1097 runs uvicorn + gRPC servers, routes via proxy_router.py to
DeploymentHandles).

Hand-rolled asyncio HTTP/1.1 server (no uvicorn in this env): parses
requests, longest-prefix route match against the controller's route table,
dispatches through a DeploymentHandle, JSON-encodes responses.
"""

from __future__ import annotations

import asyncio
import json
import time
import urllib.parse
from typing import Any, Dict, Optional, Tuple

import ray_tpu
from ray_tpu.exceptions import BackPressureError


class Request:
    """What ingress callables receive (starlette.Request analog)."""

    def __init__(self, method: str, path: str, query: Dict[str, str],
                 headers: Dict[str, str], body: bytes):
        self.method = method
        self.path = path
        self.query_params = query
        self.headers = headers
        self.body = body

    def json(self) -> Any:
        return json.loads(self.body) if self.body else None

    @property
    def text(self) -> str:
        return self.body.decode()


_REASONS = {200: "OK", 201: "Created", 204: "No Content",
            301: "Moved Permanently", 302: "Found", 400: "Bad Request",
            401: "Unauthorized", 403: "Forbidden", 404: "Not Found",
            405: "Method Not Allowed", 409: "Conflict",
            422: "Unprocessable Entity", 500: "Internal Server Error",
            503: "Service Unavailable"}


class _StreamOut:
    """A streaming response being relayed to the HTTP client."""

    def __init__(self, status: str, ctype: str, headers: Dict[str, str],
                 stream):
        self.status = status
        self.ctype = ctype
        self.headers = headers
        self._stream = stream

    async def chunks(self):
        it = iter(self._stream)
        while True:
            # each pull can block on the replica's next yield: off-loop
            chunk = await asyncio.to_thread(next, it, _DONE)
            if chunk is _DONE:
                return
            yield chunk


_DONE = object()

# the proxy computes message framing itself; relayed app headers must not
# carry their own (duplicate Content-Length is an RFC 7230 violation)
_FRAMING_HEADERS = {"content-length", "transfer-encoding", "connection",
                    "content-type"}


def _clean_headers(headers):
    return [(k, v) for k, v in (headers or {}).items()
            if k.lower() not in _FRAMING_HEADERS]


class ProxyActor:
    def __init__(self, port: int = 8000, host: str = "127.0.0.1",
                 grpc_port: Optional[int] = None):
        self.port = port
        self.host = host
        self.grpc_port = grpc_port
        self._routes: Dict[str, Tuple[str, str]] = {}
        self._handles: Dict[Tuple[str, str], Any] = {}
        self._routes_snapshot = 0
        self._server: Optional[asyncio.AbstractServer] = None
        self._grpc_server = None
        self._poll_task = None

    async def ready(self) -> int:
        """Start the HTTP server + route long-poll; returns bound port.
        Idempotent — the controller reuses it as the health probe."""
        if self._server is None:
            try:
                self._server = await asyncio.start_server(
                    self._handle_conn, self.host, self.port)
            except OSError:
                # per-node proxies all request the configured port; on a
                # single-host test cluster only one can have it — the
                # others fall back to an ephemeral port (real multi-host
                # deployments bind the same port on every node)
                self._server = await asyncio.start_server(
                    self._handle_conn, self.host, 0)
            self.port = self._server.sockets[0].getsockname()[1]
            loop = asyncio.get_running_loop()
            self._poll_task = loop.create_task(self._poll_routes())
            if self.grpc_port is not None:
                await self._start_grpc()
        return self.port

    async def _start_grpc(self) -> None:
        """gRPC ingress next to HTTP (reference: the grpc server in
        serve/_private/proxy.py, generic service in grpc_util.py)."""
        import grpc

        from ray_tpu.serve.grpc_util import make_generic_handler

        self._grpc_server = grpc.aio.server()
        self._grpc_server.add_generic_rpc_handlers(
            (make_generic_handler(self._get_handle, lambda: self._routes),))
        bound = self._grpc_server.add_insecure_port(
            f"{self.host}:{self.grpc_port}")
        if bound == 0:
            # same single-host fallback as the HTTP listener
            bound = self._grpc_server.add_insecure_port(f"{self.host}:0")
        if bound == 0:
            raise RuntimeError(
                f"gRPC ingress could not bind {self.host}:{self.grpc_port}"
                " (port in use or not permitted)")
        self.grpc_port = bound
        await self._grpc_server.start()

    async def get_grpc_port(self) -> Optional[int]:
        return self.grpc_port

    async def get_host(self) -> str:
        """The host this proxy is actually reachable on: its node's IP
        when bound to a wildcard/loopback-on-remote-node address — the
        controller records THIS, not the shared config host, so clients
        on other machines get a usable ingress address."""
        if self.host not in ("0.0.0.0", "::", ""):
            return self.host
        from ray_tpu._private.worker import node_ip

        return node_ip()

    def _controller(self):
        from ray_tpu.serve._private.controller import (
            CONTROLLER_NAME, SERVE_NAMESPACE)

        return ray_tpu.get_actor(CONTROLLER_NAME, namespace=SERVE_NAMESPACE)

    async def _poll_routes(self):
        """LongPollClient loop (reference: long_poll.py LongPollClient:66)."""
        while True:
            try:
                # everything blocking runs off-loop: resolving the named
                # controller can wait for it to come up, and a blocked loop
                # here would freeze request handling (and the ready reply)
                ctrl = await asyncio.to_thread(self._controller)
                updates = await asyncio.to_thread(
                    lambda: ray_tpu.get(
                        ctrl.listen_for_change.remote(
                            {"routes": self._routes_snapshot}, 10.0),
                        timeout=15))
                if updates and "routes" in updates:
                    sid, routes = updates["routes"]
                    self._routes_snapshot = sid
                    self._routes = routes or {}
                    # drop cached handles for apps no longer routed
                    # (deleted/redeployed apps must not pin their old
                    # handles — and their routers — forever; raylint
                    # R10). Keyed by app, not (app, dep): the generic
                    # handler fetches non-ingress deployments of LIVE
                    # apps, and those caches stay warm across updates.
                    live_apps = {app for app, _dep in self._routes.values()}
                    for key in [k for k in self._handles
                                if k[0] not in live_apps]:
                        self._handles.pop(key, None)
            except Exception:
                await asyncio.sleep(0.5)

    # ----------------------------------------------------------- HTTP server
    async def _handle_conn(self, reader: asyncio.StreamReader,
                           writer: asyncio.StreamWriter):
        try:
            while True:
                line = await reader.readline()
                if not line or line in (b"\r\n", b"\n"):
                    break
                try:
                    method, target, _version = \
                        line.decode("latin1").strip().split(" ", 2)
                except ValueError:
                    break
                headers: Dict[str, str] = {}
                while True:
                    h = await reader.readline()
                    if not h or h in (b"\r\n", b"\n"):
                        break
                    k, _, v = h.decode("latin1").partition(":")
                    headers[k.strip().lower()] = v.strip()
                body = b""
                length = int(headers.get("content-length", 0) or 0)
                if length:
                    body = await reader.readexactly(length)
                out = await self._dispatch(method, target, headers, body)
                if isinstance(out, _StreamOut):
                    # chunked transfer-encoding: flush each chunk as the
                    # replica yields it (reference: proxy streaming path)
                    hdrs = "".join(f"{k}: {v}\r\n"
                                   for k, v in _clean_headers(out.headers))
                    writer.write(
                        f"HTTP/1.1 {out.status}\r\n"
                        f"Content-Type: {out.ctype}\r\n"
                        f"Transfer-Encoding: chunked\r\n{hdrs}"
                        f"Connection: keep-alive\r\n\r\n".encode("latin1"))
                    await writer.drain()
                    try:
                        async for chunk in out.chunks():
                            data = (chunk if isinstance(chunk, bytes)
                                    else str(chunk).encode())
                            writer.write(
                                f"{len(data):x}\r\n".encode("latin1")
                                + data + b"\r\n")
                            await writer.drain()
                    except Exception:
                        # mid-stream failure: close WITHOUT the 0-length
                        # terminator so the client sees truncation, not a
                        # clean end-of-response
                        break
                    writer.write(b"0\r\n\r\n")
                    await writer.drain()
                else:
                    status, payload, ctype, extra = out
                    hdrs = "".join(f"{k}: {v}\r\n"
                                   for k, v in _clean_headers(extra))
                    writer.write(
                        f"HTTP/1.1 {status}\r\n"
                        f"Content-Type: {ctype}\r\n"
                        f"Content-Length: {len(payload)}\r\n{hdrs}"
                        f"Connection: keep-alive\r\n\r\n".encode("latin1"))
                    writer.write(payload)
                    await writer.drain()
                if headers.get("connection", "").lower() == "close":
                    break
        except (asyncio.IncompleteReadError, ConnectionError):
            pass
        finally:
            try:
                writer.close()
            except Exception:
                pass

    async def _dispatch(self, method: str, target: str,
                        headers: Dict[str, str], body: bytes):
        parsed = urllib.parse.urlsplit(target)
        path = parsed.path
        query = dict(urllib.parse.parse_qsl(parsed.query))
        if path == "/-/healthz":
            return "200 OK", b"success", "text/plain", None
        if path == "/-/routes":
            return ("200 OK",
                    json.dumps({p: a for p, (a, _) in self._routes.items()}
                               ).encode(), "application/json", None)
        match = self._match_route(path)
        if match is None:
            return "404 Not Found", b'{"error": "no route"}', \
                "application/json", None
        prefix, (app_name, ingress) = match
        # strip the normalized prefix so request.path keeps its leading "/"
        sub_path = path[len(prefix.rstrip("/")):] or "/"
        request = Request(method, sub_path, query, headers, body)
        try:
            handle = self._get_handle(app_name, ingress)
            response = handle.remote(request)
            result = await asyncio.to_thread(response.result, 60.0)
            from ray_tpu.serve.handle import _BufferedStream

            if isinstance(result, _BufferedStream):
                meta = result.meta
                code = meta.get("status_code", 200)
                return _StreamOut(
                    f"{code} {_REASONS.get(code, 'OK')}",
                    meta.get("media_type") or "application/octet-stream",
                    meta.get("headers") or {}, result)
            return self._encode(result)
        except BackPressureError as e:
            # the plane shed this request (admission queues full): tell
            # the client to back off — a typed 503, never a spin-retry
            return ("503 Service Unavailable",
                    json.dumps({"error": str(e),
                                "reason": "backpressure"}).encode(),
                    "application/json", {"Retry-After": "1"})
        except TimeoutError as e:
            return ("503 Service Unavailable",
                    json.dumps({"error": str(e)}).encode(),
                    "application/json", None)
        except Exception as e:
            if isinstance(getattr(e, "cause", None), BackPressureError):
                # shed inside the replica (e.g. a batching engine's
                # pending cap), surfaced as RayTaskError(cause=...)
                return ("503 Service Unavailable",
                        json.dumps({"error": str(e.cause),
                                    "reason": "backpressure"}).encode(),
                        "application/json", {"Retry-After": "1"})
            return ("500 Internal Server Error",
                    json.dumps({"error": f"{type(e).__name__}: {e}"}
                               ).encode(), "application/json", None)

    def _match_route(self, path: str):
        best = None
        for prefix, target in self._routes.items():
            norm = prefix.rstrip("/")
            if path == norm or path.startswith(norm + "/") or prefix == "/":
                if best is None or len(prefix) > len(best[0]):
                    best = (prefix, target)
        return best

    def _get_handle(self, app_name: str, dep_name: str):
        from ray_tpu.serve.handle import DeploymentHandle

        key = (app_name, dep_name)
        if key not in self._handles:
            self._handles[key] = DeploymentHandle(app_name, dep_name)
        return self._handles[key]

    @staticmethod
    def _encode(result: Any):
        from ray_tpu.serve.asgi import Response

        if isinstance(result, Response):
            return (f"{result.status_code} "
                    f"{_REASONS.get(result.status_code, 'OK')}",
                    result.body, result.media_type, result.headers)
        if isinstance(result, bytes):
            return "200 OK", result, "application/octet-stream", None
        if isinstance(result, str):
            return "200 OK", result.encode(), "text/plain", None
        return ("200 OK", json.dumps(result, default=str).encode(),
                "application/json", None)
