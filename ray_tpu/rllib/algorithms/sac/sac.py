"""SAC — soft actor-critic with twin Q critics, polyak targets, and
auto-tuned temperature (reference: rllib/algorithms/sac/sac.py +
sac/torch/sac_torch_learner.py; Haarnoja 2018).

One jitted update covers critic, actor, and alpha steps — three
value_and_grads fused by XLA into a single HBM-resident graph.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax

import ray_tpu
from ray_tpu.rllib.algorithms.algorithm import Algorithm
from ray_tpu.rllib.algorithms.algorithm_config import AlgorithmConfig
from ray_tpu.rllib.utils.replay_buffer import ReplayBuffer

LOG_STD_MIN, LOG_STD_MAX = -20.0, 2.0


# ------------------------------------------------------------------- module
@dataclasses.dataclass
class SACModuleSpec:
    """Actor + twin critics (reference: sac/sac_rl_module.py)."""

    obs_dim: int
    action_dim: int
    discrete: bool = False  # SAC here is continuous-only
    hiddens: Tuple[int, ...] = (256, 256)
    activation: str = "relu"

    def build(self) -> "SACModule":
        return SACModule(self)


class SACModule:
    def __init__(self, spec: SACModuleSpec):
        self.spec = spec
        self._act = {"tanh": jnp.tanh, "relu": jax.nn.relu}[spec.activation]

    def _mlp(self, key, sizes):
        layers = []
        for a, b in zip(sizes[:-1], sizes[1:]):
            key, sub = jax.random.split(key)
            layers.append({
                "w": jax.random.normal(sub, (a, b)) * jnp.sqrt(2.0 / a),
                "b": jnp.zeros((b,)),
            })
        return layers

    def init(self, rng) -> Dict:
        ka, k1, k2 = jax.random.split(rng, 3)
        h = self.spec.hiddens
        obs, act = self.spec.obs_dim, self.spec.action_dim
        return {
            "actor": self._mlp(ka, (obs, *h, 2 * act)),
            "q1": self._mlp(k1, (obs + act, *h, 1)),
            "q2": self._mlp(k2, (obs + act, *h, 1)),
            "log_alpha": jnp.asarray(0.0, jnp.float32),
        }

    def _tower(self, layers, x):
        for layer in layers[:-1]:
            x = self._act(x @ layer["w"] + layer["b"])
        last = layers[-1]
        return x @ last["w"] + last["b"]

    # squashed-Gaussian policy
    def pi(self, params, obs, rng):
        out = self._tower(params["actor"], obs)
        mean, log_std = jnp.split(out, 2, axis=-1)
        log_std = jnp.clip(log_std, LOG_STD_MIN, LOG_STD_MAX)
        std = jnp.exp(log_std)
        raw = mean + std * jax.random.normal(rng, mean.shape)
        action = jnp.tanh(raw)
        # log-prob with tanh-squash correction (SAC appendix C)
        logp_raw = jnp.sum(
            -0.5 * ((raw - mean) / std) ** 2 - log_std
            - 0.5 * jnp.log(2 * jnp.pi), axis=-1)
        logp = logp_raw - jnp.sum(
            2.0 * (jnp.log(2.0) - raw - jax.nn.softplus(-2.0 * raw)),
            axis=-1)
        return action, logp, jnp.tanh(mean)

    def q(self, params, obs, action):
        x = jnp.concatenate([obs, action], axis=-1)
        return (self._tower(params["q1"], x)[..., 0],
                self._tower(params["q2"], x)[..., 0])

    def logp(self, params, obs, action):
        """Log-density of a GIVEN squashed action under the current
        policy (offline learners — CRR/AWR-style — regress onto dataset
        actions, so they need logp at arbitrary a, not just samples)."""
        out = self._tower(params["actor"], obs)
        mean, log_std = jnp.split(out, 2, axis=-1)
        log_std = jnp.clip(log_std, LOG_STD_MIN, LOG_STD_MAX)
        raw = jnp.arctanh(jnp.clip(action, -1.0 + 1e-6, 1.0 - 1e-6))
        std = jnp.exp(log_std)
        logp_raw = jnp.sum(
            -0.5 * ((raw - mean) / std) ** 2 - log_std
            - 0.5 * jnp.log(2 * jnp.pi), axis=-1)
        return logp_raw - jnp.sum(
            2.0 * (jnp.log(2.0) - raw - jax.nn.softplus(-2.0 * raw)),
            axis=-1)

    # env-runner interface
    def forward(self, params, obs) -> Dict[str, jnp.ndarray]:
        out = self._tower(params["actor"], obs)
        mean, _ = jnp.split(out, 2, axis=-1)
        action = jnp.tanh(mean)
        q1, _ = self.q(params, obs, action)
        return {"logits": out, "vf": q1}

    def explore_action(self, params, obs, rng):
        action, logp, _ = self.pi(params, obs, rng)
        q1, _ = self.q(params, obs, action)
        return action, logp, q1

    def greedy_action(self, params, obs):
        out = self._tower(params["actor"], obs)
        mean, _ = jnp.split(out, 2, axis=-1)
        action = jnp.tanh(mean)
        q1, _ = self.q(params, obs, action)
        return action, jnp.zeros(action.shape[:-1]), q1


# ------------------------------------------------------------------ learner
class SACLearner:
    """Critic + actor + temperature updates (reference:
    sac_torch_learner.py compute_loss_for_module). Drives its own optax
    chains per component, so it implements the Learner duck-type rather
    than subclassing the PG Learner."""

    def __init__(self, module_spec: SACModuleSpec, config: Dict,
                 use_mesh: bool = True):
        self.module = module_spec.build()
        self.config = config
        self._rng = jax.random.key(config.get("seed", 0))
        self._rng, init_key = jax.random.split(self._rng)
        self.params = self.module.init(init_key)
        self.target_params = jax.tree.map(
            jnp.copy, {"q1": self.params["q1"], "q2": self.params["q2"]})
        lr = config.get("lr", 3e-4)
        self.tx = optax.adam(lr)
        self.opt_state = self.tx.init(self.params)
        self.target_entropy = config.get(
            "target_entropy", -float(module_spec.action_dim))
        self._update = self._build_update()

    def _losses(self, params, target_params, batch, k1, k2):
        """Joint critic+actor+alpha loss; overridable (CQL adds its
        conservative penalty on top, reference: cql_torch_learner)."""
        gamma = self.config.get("gamma", 0.99)
        target_entropy = self.target_entropy
        alpha = jnp.exp(params["log_alpha"])
        # ---- critic target
        next_a, next_logp, _ = self.module.pi(params, batch["next_obs"], k1)
        tq1, tq2 = self.module.q(
            {**params, "q1": target_params["q1"],
             "q2": target_params["q2"]},
            batch["next_obs"], next_a)
        q_next = jnp.minimum(tq1, tq2) - \
            jax.lax.stop_gradient(alpha) * next_logp
        target = batch["rewards"] + gamma * (1 - batch["dones"]) * q_next
        target = jax.lax.stop_gradient(target)
        q1, q2 = self.module.q(params, batch["obs"], batch["actions"])
        critic_loss = jnp.mean((q1 - target) ** 2) + \
            jnp.mean((q2 - target) ** 2)
        # ---- actor
        new_a, logp, _ = self.module.pi(params, batch["obs"], k2)
        pq1, pq2 = self.module.q(jax.lax.stop_gradient(params),
                                 batch["obs"], new_a)
        actor_loss = jnp.mean(
            jax.lax.stop_gradient(alpha) * logp - jnp.minimum(pq1, pq2))
        # ---- temperature
        alpha_loss = -jnp.mean(
            params["log_alpha"] *
            jax.lax.stop_gradient(logp + target_entropy))
        total = critic_loss + actor_loss + alpha_loss
        return total, {
            "critic_loss": critic_loss, "actor_loss": actor_loss,
            "alpha_loss": alpha_loss, "alpha": alpha,
            "qf_mean": jnp.mean(q1), "entropy": -jnp.mean(logp),
        }

    def _build_update(self):
        tau = self.config.get("tau", 0.005)

        def update(params, target_params, opt_state, batch, rng):
            rng, k1, k2 = jax.random.split(rng, 3)
            (loss, metrics), grads = jax.value_and_grad(
                self._losses, has_aux=True)(params, target_params, batch,
                                            k1, k2)
            updates, opt_state = self.tx.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            target_params = jax.tree.map(
                lambda t, o: (1 - tau) * t + tau * o, target_params,
                {"q1": params["q1"], "q2": params["q2"]})
            metrics["total_loss"] = loss
            return params, target_params, opt_state, metrics, rng

        return jax.jit(update)

    def update(self, batch: Dict[str, np.ndarray]) -> Dict[str, float]:
        self.params, self.target_params, self.opt_state, metrics, self._rng \
            = self._update(self.params, self.target_params, self.opt_state,
                           batch, self._rng)
        return {k: float(v) for k, v in metrics.items()}

    # Learner duck-type
    def get_weights(self):
        return jax.device_get(self.params)

    def set_weights(self, weights) -> None:
        self.params = jax.device_put(weights)

    def get_state(self) -> Dict:
        return {"params": jax.device_get(self.params),
                "target_params": jax.device_get(self.target_params),
                "opt_state": jax.device_get(self.opt_state)}

    def set_state(self, state: Dict) -> None:
        self.params = state["params"]
        self.target_params = state["target_params"]
        self.opt_state = state["opt_state"]


# ---------------------------------------------------------------- algorithm
class SACConfig(AlgorithmConfig):
    def __init__(self, algo_class=None):
        super().__init__(algo_class=algo_class or SAC)
        self.lr = 3e-4
        self.train_batch_size = 256
        self.replay_buffer_capacity = 100_000
        self.num_steps_sampled_before_learning_starts = 1500
        self.tau = 0.005
        self.target_entropy = None  # None -> -action_dim
        self.training_intensity = 1.0
        self.rollout_fragment_length = 1
        self.num_env_runners = 1
        self.model = {"hiddens": (256, 256), "activation": "relu"}

    def _training_keys(self):
        return {"replay_buffer_capacity", "tau", "target_entropy",
                "num_steps_sampled_before_learning_starts",
                "training_intensity"}

    def learner_config_dict(self) -> Dict:
        d = super().learner_config_dict()
        d["tau"] = self.tau
        if self.target_entropy is not None:
            d["target_entropy"] = self.target_entropy
        return d

    def module_spec(self) -> SACModuleSpec:
        base = super().module_spec()
        if base.discrete:
            raise ValueError("this SAC implements continuous control only")
        return SACModuleSpec(
            obs_dim=base.obs_dim, action_dim=base.action_dim,
            hiddens=tuple(self.model.get("hiddens", (256, 256))),
            activation=self.model.get("activation", "relu"))


class SAC(Algorithm):
    learner_cls = SACLearner

    @classmethod
    def get_default_config(cls):
        return SACConfig(algo_class=cls)

    def setup(self, _config) -> None:
        super().setup(_config)
        self.replay = ReplayBuffer(self.config.replay_buffer_capacity,
                                   seed=self.config.seed)

    def _make_runner(self, idx: int):
        cfg = self.config
        from ray_tpu.rllib.env.env_runner import SingleAgentEnvRunner

        return ray_tpu.remote(SingleAgentEnvRunner).options(
            resources={"CPU": 1}).remote(
                cfg.make_env(), cfg.num_envs_per_env_runner,
                cfg.rollout_fragment_length, self._module_spec,
                seed=cfg.seed + idx * 1000 + 1, explore=cfg.explore,
                gamma=cfg.gamma, collect_next_obs=True,
                connector=cfg.connector)

    def training_step(self) -> Dict:
        cfg = self.config
        learner = self.learner_group.local_learner()
        weights_ref = ray_tpu.put(learner.get_weights())

        samples = self._sample_from_runners(weights_ref)
        new_steps = sum(s["env_steps"] for s in samples)
        for s in samples:
            flat = lambda a: a.reshape((-1,) + a.shape[2:])
            mask = flat(s["valid"])
            self.replay.add_batch({
                "obs": flat(s["obs"])[mask],
                "actions": flat(s["actions"])[mask],
                "rewards": flat(s["rewards"])[mask],
                "next_obs": flat(s["next_obs"])[mask],
                "dones": flat(s["dones"])[mask],
            })

        metrics: Dict = {"env_steps_this_iter": new_steps}
        if len(self.replay) < cfg.num_steps_sampled_before_learning_starts:
            return metrics
        # training_intensity = replayed/sampled step ratio (same semantics
        # as DQN): updates * batch_size ~= new_steps * intensity
        num_updates = max(1, int(new_steps * cfg.training_intensity /
                                 max(cfg.train_batch_size, 1)))
        for _ in range(num_updates):
            metrics.update(learner.update(
                self.replay.sample(cfg.train_batch_size)))
        return metrics
