"""Client-mode driver stub (reference: python/ray/util/client/worker.py —
the Worker that proxies the ray API over the connection, and
client_builder.py for ``ray.init("ray://...")``)."""

from __future__ import annotations

import asyncio
import threading
import weakref
from typing import Any, Dict, List, Optional, Tuple

from ray_tpu._private import serialization as ser
from ray_tpu._private.config import CONFIG


# Deadline for client data-plane RPCs (put/get/task/actor submissions):
# one bound to retune, mirrored by _Channel.call's default. Gets/waits
# with a user timeout get +10s slack so the server-side answer wins.
_DATA_RPC_TIMEOUT_S = 300.0


class _Channel:
    """Sync RPC facade over a private event-loop thread."""

    def __init__(self, host: str, port: int):
        self._loop = asyncio.new_event_loop()
        self._thread = threading.Thread(
            target=self._loop.run_forever, name="ray-client", daemon=True)
        self._thread.start()
        from ray_tpu._private.protocol import AsyncRpcClient

        self.client = AsyncRpcClient()
        fut = asyncio.run_coroutine_threadsafe(
            self.client.connect_tcp(host, port), self._loop)
        fut.result(30)

    def call(self, method: str, payload: Dict,
             timeout: float = _DATA_RPC_TIMEOUT_S):
        fut = asyncio.run_coroutine_threadsafe(
            self.client.call(method, payload), self._loop)
        return fut.result(timeout)

    def close(self) -> None:
        # aclose ON the private loop BEFORE stopping it: stopping first
        # strands the client's cancelled read-loop task, which the dying
        # loop reports as "Task was destroyed but it is pending!" at
        # interpreter teardown (the BENCH tail-leak shape)
        try:
            asyncio.run_coroutine_threadsafe(
                self.client.aclose(), self._loop).result(5)
        except Exception:
            pass
        self._loop.call_soon_threadsafe(self._loop.stop)
        self._thread.join(timeout=5)


class ClientObjectRef:
    """Names a ref held by the server on this client's behalf."""

    def __init__(self, ctx: "ClientContext", hex_id: str):
        self._ctx = ctx
        self._hex = hex_id
        weakref.finalize(self, ctx._release_later, hex_id)

    def hex(self) -> str:
        return self._hex

    def __repr__(self) -> str:
        return f"ClientObjectRef({self._hex})"


class ClientActorMethod:
    def __init__(self, ctx: "ClientContext", actor_id: str, name: str,
                 opts: Optional[Dict] = None):
        self._ctx = ctx
        self._actor_id = actor_id
        self._name = name
        self._opts = opts

    def options(self, **opts) -> "ClientActorMethod":
        return ClientActorMethod(self._ctx, self._actor_id, self._name, opts)

    def remote(self, *args, **kwargs):
        return self._ctx._actor_call(self._actor_id, self._name, args,
                                     kwargs, self._opts)


class ClientActorHandle:
    def __init__(self, ctx: "ClientContext", actor_id: str):
        self._ctx = ctx
        self._actor_id = actor_id

    def __getattr__(self, name: str) -> ClientActorMethod:
        if name.startswith("_"):
            raise AttributeError(name)
        return ClientActorMethod(self._ctx, self._actor_id, name)


class ClientRemoteFunction:
    def __init__(self, ctx: "ClientContext", fn, opts: Optional[Dict] = None):
        self._ctx = ctx
        self._fn = fn
        self._opts = opts or {}

    def options(self, **opts) -> "ClientRemoteFunction":
        return ClientRemoteFunction(self._ctx, self._fn,
                                    {**self._opts, **opts})

    def remote(self, *args, **kwargs):
        return self._ctx._task(self._fn, args, kwargs, self._opts)


class ClientActorClass:
    def __init__(self, ctx: "ClientContext", cls, opts: Optional[Dict] = None):
        self._ctx = ctx
        self._cls = cls
        self._opts = opts or {}

    def options(self, **opts) -> "ClientActorClass":
        return ClientActorClass(self._ctx, self._cls, {**self._opts, **opts})

    def remote(self, *args, **kwargs) -> ClientActorHandle:
        return self._ctx._create_actor(self._cls, args, kwargs, self._opts)


class ClientContext:
    """The ray API, proxied (returned by ``connect`` /
    ``ray_tpu.init("ray://...")``)."""

    def __init__(self, host: str, port: int,
                 init_kwargs: Optional[Dict] = None):
        self._chan = _Channel(host, port)
        self._pending_release: List[str] = []
        # RLock: _release_later runs in GC context (weakref.finalize on
        # ClientObjectRef) and may fire mid-critical-section on the very
        # thread holding this lock (raylint R1, the MemoryStore class)
        self._lock = threading.RLock()
        # data-plane budget, not control_rpc_timeout_s: the server-side
        # handler runs a full ray_tpu.init() cluster bring-up (GCS,
        # agents, prestart workers), not an immediate answer
        self._chan.call("ClientInit", {
            "init_kwargs": ser.dumps(init_kwargs or {})},
            timeout=_DATA_RPC_TIMEOUT_S)

    # --------------------------------------------------------------- helpers
    def _wire_args(self, args: tuple, kwargs: dict) -> Tuple[List, Dict]:
        def enc(v):
            if isinstance(v, ClientObjectRef):
                return {"ref": v.hex()}
            return {"v": ser.dumps(v)}

        return [enc(a) for a in args], {k: enc(v) for k, v in kwargs.items()}

    def _refs_from(self, reply) -> Any:
        refs = [ClientObjectRef(self, r["id"]) for r in reply["refs"]]
        return refs[0] if len(refs) == 1 else refs

    def _release_later(self, hex_id: str) -> None:
        with self._lock:
            self._pending_release.append(hex_id)

    def _flush_releases(self) -> None:
        with self._lock:
            batch, self._pending_release = self._pending_release, []
        if batch:
            try:
                self._chan.call("ClientRelease", {"ids": batch},
                                timeout=CONFIG.control_rpc_timeout_s)
            except Exception:
                pass

    # ------------------------------------------------------------------ api
    def remote(self, fn_or_cls=None, **opts):
        import inspect

        def wrap(target):
            if inspect.isclass(target):
                return ClientActorClass(self, target, opts)
            return ClientRemoteFunction(self, target, opts)

        if fn_or_cls is None:
            return wrap
        return wrap(fn_or_cls)

    def put(self, value: Any) -> ClientObjectRef:
        self._flush_releases()
        reply = self._chan.call("ClientPut", {"value": ser.dumps(value)},
                                timeout=_DATA_RPC_TIMEOUT_S)
        return self._refs_from(reply)

    def get(self, refs, timeout: Optional[float] = None):
        self._flush_releases()
        single = isinstance(refs, ClientObjectRef)
        if single:
            refs = [refs]
        reply = self._chan.call(
            "ClientGet", {"ids": [r.hex() for r in refs], "timeout": timeout},
            timeout=(timeout + 10) if timeout else _DATA_RPC_TIMEOUT_S)
        if reply.get("error"):
            raise ser.loads(bytes(reply["error"]))
        values = [ser.loads(bytes(v)) for v in reply["values"]]
        return values[0] if single else values

    def wait(self, refs, num_returns: int = 1,
             timeout: Optional[float] = None):
        reply = self._chan.call("ClientWait", {
            "ids": [r.hex() for r in refs], "num_returns": num_returns,
            "timeout": timeout},
            timeout=(timeout + 10) if timeout else _DATA_RPC_TIMEOUT_S)
        by_hex = {r.hex(): r for r in refs}
        return ([by_hex[h] for h in reply["ready"]],
                [by_hex[h] for h in reply["not_ready"]])

    def _task(self, fn, args, kwargs, opts):
        self._flush_releases()
        wa, wk = self._wire_args(args, kwargs)
        reply = self._chan.call("ClientTask", {
            "fn": ser.dumps(fn), "args": wa, "kwargs": wk,
            "opts": ser.dumps(opts) if opts else None},
            timeout=_DATA_RPC_TIMEOUT_S)
        return self._refs_from(reply)

    def _create_actor(self, cls, args, kwargs, opts) -> ClientActorHandle:
        wa, wk = self._wire_args(args, kwargs)
        reply = self._chan.call("ClientCreateActor", {
            "cls": ser.dumps(cls), "args": wa, "kwargs": wk,
            "opts": ser.dumps(opts) if opts else None},
            timeout=_DATA_RPC_TIMEOUT_S)
        return ClientActorHandle(self, reply["actor_id"])

    def _actor_call(self, actor_id, method, args, kwargs, opts):
        wa, wk = self._wire_args(args, kwargs)
        reply = self._chan.call("ClientActorCall", {
            "actor_id": actor_id, "method": method, "args": wa, "kwargs": wk,
            "opts": ser.dumps(opts) if opts else None},
            timeout=_DATA_RPC_TIMEOUT_S)
        return self._refs_from(reply)

    def get_actor(self, name: str,
                  namespace: Optional[str] = None) -> ClientActorHandle:
        reply = self._chan.call("ClientGetNamedActor",
                                {"name": name, "namespace": namespace},
                                timeout=CONFIG.control_rpc_timeout_s)
        return ClientActorHandle(self, reply["actor_id"])

    def kill(self, actor: ClientActorHandle, no_restart: bool = True) -> None:
        self._chan.call("ClientKill", {"actor_id": actor._actor_id,
                                       "no_restart": no_restart},
                        timeout=CONFIG.control_rpc_timeout_s)

    def cancel(self, ref: ClientObjectRef, force: bool = False) -> None:
        self._chan.call("ClientCancel", {"id": ref.hex(), "force": force},
                        timeout=CONFIG.control_rpc_timeout_s)

    def nodes(self) -> List[Dict]:
        return self._chan.call("ClientClusterInfo", {},
                               timeout=CONFIG.control_rpc_timeout_s)["nodes"]

    def cluster_resources(self) -> Dict[str, float]:
        reply = self._chan.call("ClientClusterInfo", {},
                                timeout=CONFIG.control_rpc_timeout_s)
        return reply["resources"]

    def disconnect(self) -> None:
        self._chan.close()


def connect(address: str, **init_kwargs) -> ClientContext:
    """Connect to a ``ray://host:port`` client server."""
    addr = address[len("ray://"):] if address.startswith("ray://") else address
    host, _, port = addr.partition(":")
    return ClientContext(host, int(port or 10001), init_kwargs or None)
