from ray_tpu.util.collective.collective_group.base_group import BaseGroup
from ray_tpu.util.collective.collective_group.cpu_group import CPUGroup
from ray_tpu.util.collective.collective_group.xla_group import XLAGroup

__all__ = ["BaseGroup", "CPUGroup", "XLAGroup"]
