"""R14 — wire-frame contract drift between send and receive paths.

Invariant: for every RPC/stream method, the msgpack payload keys built
on send paths and the keys read on the registered receive path must
agree — no send-only keys (dead bytes on every frame, or worse, a
feature the receiver silently ignores), no read-but-never-sent keys
(``.get`` masking a key that no sender provides), no type-incoherent
keys (the same key sent as ``str`` in one caller and ``int`` in
another).

Motivating shape (PR 11/18): the mux/shm/batch framing contracts —
single-letter keys like ``"s"`` (stream id), ``"q"`` (session seq),
``"ai"`` (assigned instances) riding ``PushTaskBatchStream`` — hold by
convention only; a typo'd key on one of five send sites ships silently
and surfaces as a hang three modules away.

Detection: send sites are ``client.call/call_future/push/push_nowait/
call_raw_into("Method", {...})`` and ``head_call("Method", {...})``
with a CamelCase string-literal method; thin *send wrappers* — a
function that forwards a method parameter and a payload parameter into
one of those verbs, like ``util/state``'s ``_call(method, payload)`` —
are detected and their call sites indexed as send sites too. Receive
sites come from ``add_handler("Method", fn)`` (including the
``r = server.add_handler`` alias idiom) and ``@server.route("Method")``.
Handler payload reads are
``p["k"]`` / ``p.get("k")`` / ``"k" in p``; any opaque use of the
payload (iterated, forwarded) disables the send-only check for that
method, and the read-never-sent check requires every send site to be a
full dict literal. Optional keys (sent by some literal sites, absent
from others) are fine by design.
"""

from __future__ import annotations

import ast
import re
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set, Tuple

from ..callgraph import (AMBIGUITY_CUTOFF, FunctionInfo, ProjectIndex,
                         _call_name)
from ..model import ModuleInfo, Violation

RULE_ID = "R14"
SUMMARY = ("msgpack frame contract drift: payload key sent but never "
           "read, read but never sent, or sent with incoherent types "
           "across send sites")

_SEND_VERBS = {"call", "call_future", "push", "push_nowait",
               "call_raw_into", "head_call"}
_METHOD_RE = re.compile(r"^[A-Z][A-Za-z0-9]{2,}$")


@dataclass
class _SendSite:
    mod: ModuleInfo
    call: ast.Call
    keys: Dict[str, Tuple[Optional[str], ast.AST]]  # key -> (type, node)
    literal: bool      # full dict literal, no ** expansion


@dataclass
class _Recv:
    mod: ModuleInfo
    fn: FunctionInfo
    reads: Dict[str, ast.AST] = field(default_factory=dict)
    opaque: bool = False


def _type_tag(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Constant):
        return type(node.value).__name__
    if isinstance(node, ast.Dict):
        return "dict"
    if isinstance(node, (ast.List, ast.Tuple, ast.Set, ast.ListComp)):
        return "list"
    if isinstance(node, ast.JoinedStr):
        return "str"
    return None  # Name / Call / computed — unknown


def _compatible(a: str, b: str) -> bool:
    if a == b or "NoneType" in (a, b):
        return True
    return {a, b} <= {"int", "float"}


def _payload_site(mod: ModuleInfo, call: ast.Call,
                  payload: ast.AST) -> Optional[_SendSite]:
    if not isinstance(payload, ast.Dict):
        return None
    keys: Dict[str, Tuple[Optional[str], ast.AST]] = {}
    literal = True
    for k, v in zip(payload.keys, payload.values):
        if k is None:                       # ** expansion
            literal = False
        elif isinstance(k, ast.Constant) and isinstance(k.value, str):
            keys[k.value] = (_type_tag(v), k)
        else:
            literal = False                 # computed key
    return _SendSite(mod, call, keys, literal)


def _param_name(expr: ast.AST) -> Optional[str]:
    """The parameter a wrapper forwards: a bare ``payload`` Name, or the
    ``payload or {}`` defaulting idiom."""
    if isinstance(expr, ast.BoolOp) and isinstance(expr.op, ast.Or) \
            and expr.values and isinstance(expr.values[0], ast.Name):
        return expr.values[0].id
    return expr.id if isinstance(expr, ast.Name) else None


def _send_wrappers(index: ProjectIndex) -> Dict[Tuple[str, str],
                                                Tuple[int, int]]:
    """(relpath, name) → (method_arg_idx, payload_arg_idx) for thin
    module-level wrappers that forward both positions into a send verb
    (the ``util/state._call(method, payload)`` idiom)."""
    out: Dict[Tuple[str, str], Tuple[int, int]] = {}
    for (relpath, name), fi in index.module_functions.items():
        args = getattr(fi.node, "args", None)
        if args is None:
            continue
        params = [a.arg for a in args.args if a.arg != "self"]
        for node in ast.walk(fi.node):
            if not isinstance(node, ast.Call):
                continue
            _base, attr = _call_name(node.func)
            if attr not in _SEND_VERBS or len(node.args) < 2:
                continue
            mname = _param_name(node.args[0])
            pname = _param_name(node.args[1])
            if mname in params and pname in params and mname != pname:
                out[(relpath, name)] = (params.index(mname),
                                        params.index(pname))
                break
    return out


def _resolve_handler(index: ProjectIndex, mod: ModuleInfo,
                     expr: ast.AST) -> List[FunctionInfo]:
    if isinstance(expr, ast.Attribute) and isinstance(
            expr.value, ast.Name) and expr.value.id == "self":
        cname = next((a.name for a in mod.ancestors(expr)
                      if isinstance(a, ast.ClassDef)), None)
        seen: Set[str] = set()
        while cname and cname not in seen:
            seen.add(cname)
            for ci in index.classes.get(cname, []):
                if expr.attr in ci.methods:
                    return [ci.methods[expr.attr]]
            cands = index.classes.get(cname)
            cname = None
            if cands:
                for b in cands[0].bases:
                    if b in index.classes:
                        cname = b
                        break
        return []
    if isinstance(expr, ast.Name):
        fi = index.module_functions.get((mod.relpath, expr.id))
        return [fi] if fi else []
    if isinstance(expr, ast.Attribute):
        cands = index.by_method_name.get(expr.attr, [])
        return cands if 0 < len(cands) <= AMBIGUITY_CUTOFF else []
    return []


def _payload_param(fn: FunctionInfo) -> Optional[str]:
    args = getattr(fn.node, "args", None)
    if args is None:
        return None
    names = [a.arg for a in args.args if a.arg != "self"]
    if len(names) < 2:      # handlers are (conn, payload)
        return None
    return names[-1]


def _scan_handler(index: ProjectIndex, fn: FunctionInfo) -> _Recv:
    recv = _Recv(fn.module, fn)
    pname = _payload_param(fn)
    if pname is None:
        recv.opaque = True
        return recv
    mod = fn.module
    for node in ast.walk(fn.node):
        if not (isinstance(node, ast.Name) and node.id == pname):
            continue
        parent = mod.parent(node)
        if isinstance(parent, ast.Subscript) and parent.value is node:
            sl = parent.slice
            if isinstance(sl, ast.Constant) and isinstance(sl.value, str):
                recv.reads.setdefault(sl.value, parent)
            else:
                recv.opaque = True
        elif isinstance(parent, ast.Attribute) and parent.value is node:
            gp = mod.parent(parent)
            if (parent.attr in ("get", "pop", "setdefault")
                    and isinstance(gp, ast.Call) and gp.func is parent
                    and gp.args
                    and isinstance(gp.args[0], ast.Constant)
                    and isinstance(gp.args[0].value, str)):
                recv.reads.setdefault(gp.args[0].value, gp)
            else:
                recv.opaque = True
        elif isinstance(parent, ast.Compare) and any(
                isinstance(op, (ast.In, ast.NotIn))
                for op in parent.ops) and node in parent.comparators:
            if isinstance(parent.left, ast.Constant) and isinstance(
                    parent.left.value, str):
                recv.reads.setdefault(parent.left.value, parent)
            else:
                recv.opaque = True
        elif isinstance(parent, ast.arg) or parent is None:
            continue
        else:
            # payload forwarded / iterated / defaulted — unknown reads
            recv.opaque = True
    return recv


def check(index: ProjectIndex) -> List[Violation]:
    sends: Dict[str, List[_SendSite]] = {}
    recvs: Dict[str, List[_Recv]] = {}
    wrappers = _send_wrappers(index)

    def add_send(mod: ModuleInfo, node: ast.Call, method: str,
                 payload: Optional[ast.AST]) -> None:
        if payload is None:
            # wrapper call with the payload argument omitted: the
            # wrapper's ``payload or {}`` default sends an empty frame
            site = _SendSite(mod, node, {}, literal=True)
        else:
            site = _payload_site(mod, node, payload)
            if site is None:
                site = _SendSite(mod, node, {}, literal=False)
        sends.setdefault(method, []).append(site)

    for mod in index.modules:
        alias_names: Set[str] = set()
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Assign) and isinstance(
                    node.value, ast.Attribute) \
                    and node.value.attr == "add_handler":
                for tgt in node.targets:
                    if isinstance(tgt, ast.Name):
                        alias_names.add(tgt.id)
        for node in ast.walk(mod.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for deco in node.decorator_list:
                    if (isinstance(deco, ast.Call)
                            and isinstance(deco.func, ast.Attribute)
                            and deco.func.attr == "route" and deco.args
                            and isinstance(deco.args[0], ast.Constant)):
                        cls = next((a.name for a in mod.ancestors(node)
                                    if isinstance(a, ast.ClassDef)), None)
                        fi = FunctionInfo(node.name, mod.qualname(node),
                                          mod, node, class_name=cls)
                        recvs.setdefault(deco.args[0].value, []).append(
                            _scan_handler(index, fi))
                continue
            if not isinstance(node, ast.Call):
                continue
            base, attr = _call_name(node.func)
            # handler registration (direct or via the `r = ...` alias)
            is_reg = (attr == "add_handler"
                      or (base is None and attr in alias_names))
            if is_reg and len(node.args) >= 2 and isinstance(
                    node.args[0], ast.Constant) and isinstance(
                        node.args[0].value, str):
                for fi in _resolve_handler(index, mod, node.args[1]):
                    recvs.setdefault(node.args[0].value, []).append(
                        _scan_handler(index, fi))
                continue
            # direct send site
            if (attr in _SEND_VERBS and len(node.args) >= 2
                    and isinstance(node.args[0], ast.Constant)
                    and isinstance(node.args[0].value, str)
                    and _METHOD_RE.match(node.args[0].value)):
                add_send(mod, node, node.args[0].value, node.args[1])
                continue
            # send-wrapper call site (same-module bare name)
            if base is None and isinstance(node.func, ast.Name):
                spec = wrappers.get((mod.relpath, node.func.id))
                if spec is not None:
                    mi, pi = spec
                    if (len(node.args) > mi
                            and isinstance(node.args[mi], ast.Constant)
                            and isinstance(node.args[mi].value, str)
                            and _METHOD_RE.match(node.args[mi].value)):
                        payload = (node.args[pi]
                                   if len(node.args) > pi else None)
                        add_send(mod, node, node.args[mi].value, payload)

    out: List[Violation] = []
    for method in sorted(set(sends) & set(recvs)):
        ssites = sends[method]
        handlers = recvs[method]
        reads: Set[str] = set()
        opaque = False
        for r in handlers:
            reads |= set(r.reads)
            opaque = opaque or r.opaque
        all_sent: Set[str] = set()
        for s in ssites:
            all_sent |= set(s.keys)
        hname = handlers[0].fn.qualname
        hloc = (f"{handlers[0].mod.relpath}:"
                f"{getattr(handlers[0].fn.node, 'lineno', 0)}")

        if not opaque:
            flagged: Set[str] = set()
            for s in ssites:
                for key in sorted(set(s.keys) - reads - flagged):
                    flagged.add(key)
                    _t, knode = s.keys[key]
                    out.append(s.mod.violation(
                        RULE_ID, knode,
                        f"payload key '{key}' of RPC '{method}' is "
                        f"sent here but never read by its handler "
                        f"'{hname}' ({hloc}) — dead bytes on every "
                        f"frame or a silently-ignored feature; drop "
                        f"the key or read it"))

        if ssites and all(s.literal for s in ssites):
            for r in handlers:
                for key in sorted(set(r.reads) - all_sent):
                    out.append(r.mod.violation(
                        RULE_ID, r.reads[key],
                        f"handler '{hname}' reads payload key "
                        f"'{key}' of RPC '{method}', but none of the "
                        f"{len(ssites)} literal send site(s) ever "
                        f"sends it — the read can only see the "
                        f"default; fix the key or delete the read"))

        tags: Dict[str, Tuple[str, _SendSite]] = {}
        for s in ssites:
            for key, (tag, knode) in sorted(s.keys.items()):
                if tag is None:
                    continue
                prev = tags.get(key)
                if prev is None:
                    tags[key] = (tag, s)
                elif not _compatible(prev[0], tag):
                    out.append(s.mod.violation(
                        RULE_ID, knode,
                        f"payload key '{key}' of RPC '{method}' is "
                        f"sent as {tag} here but as {prev[0]} at "
                        f"{prev[1].mod.relpath}:"
                        f"{getattr(prev[1].call, 'lineno', 0)} — "
                        f"type-incoherent wire contract; the handler "
                        f"'{hname}' cannot rely on either"))
    return out
