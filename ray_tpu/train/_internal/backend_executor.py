"""BackendExecutor: drives the worker group through backend setup and the
user train loop (reference: python/ray/train/_internal/backend_executor.py —
start :124 → Backend.on_start :190, start_training :438,
get_next_results :552)."""

from __future__ import annotations

import time
from collections import defaultdict
from typing import Any, Callable, Dict, List, Optional

from ray_tpu.exceptions import (
    ActorUnavailableError, NodeDiedError, RayActorError,
    TrainingWorkerError, WorkerCrashedError)
from ray_tpu.train._internal.session import TrainingResult
from ray_tpu.train._internal.worker_group import WorkerGroup

# a worker's pending result ref resolving to one of these = the worker
# process (or its host) is gone, not the user loop
_DEATH_ERRORS = (RayActorError, ActorUnavailableError, WorkerCrashedError,
                 NodeDiedError)


class Backend:
    """Framework plugin ABC (reference: train/backend.py:27)."""

    def on_start(self, worker_group: WorkerGroup, backend_config) -> None:
        pass

    def on_training_start(self, worker_group: WorkerGroup, backend_config) -> None:
        pass

    def on_shutdown(self, worker_group: WorkerGroup, backend_config) -> None:
        pass


class BackendExecutor:
    def __init__(self, backend_config, num_workers: int,
                 resources_per_worker: Dict[str, float],
                 placement_group=None):
        self._backend_config = backend_config
        self._backend: Backend = backend_config.backend_cls()
        self._num_workers = num_workers
        self._resources = resources_per_worker
        self._pg = placement_group
        self.worker_group: Optional[WorkerGroup] = None
        self._ranks: List[Dict] = []
        self._done_workers: set = set()
        # newest in-store checkpoint step the trainer has re-owned; acked
        # to workers on the next result round so they release keepalives
        self._acked_shard_step: Optional[int] = None

    @staticmethod
    def assign_ranks(metas: List[Dict]) -> List[Dict]:
        """Stable rank assignment by (node, order): world_rank follows the
        actor creation order, local ranks group by node, node_rank by
        first-seen node order, local_world_size per node."""
        per_node: Dict[str, int] = defaultdict(int)
        node_order: Dict[str, int] = {}
        ranks: List[Dict] = []
        for world_rank, meta in enumerate(metas):
            node = meta["node_id"]
            if node not in node_order:
                node_order[node] = len(node_order)
            ranks.append({
                "world_rank": world_rank,
                "local_rank": per_node[node],
                "node_rank": node_order[node],
                "node_id": node,
            })
            per_node[node] += 1
        for r in ranks:
            r["local_world_size"] = per_node[r["node_id"]]
        return ranks

    def start(self) -> None:
        self.worker_group = WorkerGroup(
            self._num_workers, self._resources, self._pg)
        metas = self.worker_group.node_metas()
        self._ranks = self.assign_ranks(metas)
        self._backend.on_start(self.worker_group, self._backend_config)

    @property
    def ranks(self) -> List[Dict]:
        return self._ranks

    def start_training(
        self,
        train_fn: Callable,
        config: Dict,
        experiment_name: str,
        storage_path: str,
        trial_dir: str,
        checkpoint_path: Optional[str] = None,
        dataset_shards: Optional[List[Dict[str, Any]]] = None,
        checkpoint_shards: Optional[Dict] = None,
        start_iteration: int = 0,
    ) -> None:
        from ray_tpu._private import serialization as ser

        import ray_tpu

        blob = ser.dumps(train_fn)
        inits = []
        for i, (w, r) in enumerate(zip(self.worker_group.workers, self._ranks)):
            shards = dataset_shards[i] if dataset_shards else {}
            inits.append(w.init_train_session.remote(
                world_rank=r["world_rank"],
                world_size=self._num_workers,
                local_rank=r["local_rank"],
                local_world_size=r["local_world_size"],
                node_rank=r["node_rank"],
                experiment_name=experiment_name,
                storage_path=storage_path,
                trial_dir=trial_dir,
                config=config,
                checkpoint_path=checkpoint_path,
                dataset_shards=shards,
                checkpoint_shards=checkpoint_shards,
                start_iteration=start_iteration,
            ))
        ray_tpu.get(inits)
        self._done_workers = set()
        self._backend.on_training_start(self.worker_group, self._backend_config)
        ray_tpu.get([w.start_training.remote(blob)
                     for w in self.worker_group.workers])

    def ack_in_store(self, step: int) -> None:
        """Record that in-store shards up to ``step`` are re-owned and
        pinned driver-side (CheckpointManager.register_in_store done)."""
        if self._acked_shard_step is None or step > self._acked_shard_step:
            self._acked_shard_step = step

    def get_next_results(self, timeout: Optional[float] = None
                         ) -> Optional[List[TrainingResult]]:
        """One result from every still-running worker — a sync barrier per
        report round. Returns None once all workers are DONE. Workers that
        already returned DONE are not re-polled (their queues are empty;
        uneven report counts across ranks must not wedge the round).

        Failure detection: instead of one bulk ``get`` that would block
        behind survivors wedged in a collective, each worker's ref is
        polled independently — the FIRST detected death converts the
        round into a typed :class:`TrainingWorkerError` carrying every
        failed rank seen so far plus the victim's ``DeathContext``, so
        the trainer's recovery loop can tear the group down immediately.
        """
        import ray_tpu
        from ray_tpu._private.config import CONFIG

        if timeout is None:
            timeout = CONFIG.train_result_timeout_s
        live = [i for i in range(len(self.worker_group.workers))
                if i not in self._done_workers]
        if not live:
            return None
        pending = {
            i: self.worker_group.workers[i].get_next.remote(
                timeout, release_upto=self._acked_shard_step)
            for i in live
        }
        deadline = time.monotonic() + timeout
        results: Dict[int, TrainingResult] = {}
        failed: Dict[int, Exception] = {}
        while pending and not failed:
            ready, _ = ray_tpu.wait(
                list(pending.values()), num_returns=1,
                timeout=min(1.0, max(0.05, deadline - time.monotonic())))
            for ref in ready:
                idx = next(i for i, r in pending.items() if r is ref)
                del pending[idx]
                try:
                    results[idx] = TrainingResult.from_wire(ray_tpu.get(ref))
                except _DEATH_ERRORS as e:
                    failed[idx] = e
            if not ready and time.monotonic() >= deadline:
                ranks = sorted(self._ranks[i]["world_rank"] for i in pending)
                raise TrainingWorkerError(
                    failed_ranks=ranks, reason="result round timed out",
                    message=(f"no result from rank(s) {ranks} within "
                             f"{timeout:.0f}s"))
        if failed:
            first = failed[min(failed)]
            ctx = getattr(first, "context", None)
            raise TrainingWorkerError(
                failed_ranks=sorted(self._ranks[i]["world_rank"]
                                    for i in failed),
                node_id=getattr(ctx, "node_id", ""),
                incarnation=getattr(ctx, "incarnation", 0),
                reason=getattr(ctx, "reason", "") or "worker died",
                timeline=getattr(ctx, "timeline", None)) from first
        out = []
        for i in sorted(results):
            r = results[i]
            r.world_rank = self._ranks[i]["world_rank"]
            out.append(r)
        errors = [r for r in out if r.kind == TrainingResult.ERROR]
        if errors:
            raise TrainingWorkerError(
                errors[0].error,
                failed_ranks=[r.world_rank for r in errors],
                reason="train_fn_error")
        for i, r in zip(sorted(results), out):
            if r.kind == TrainingResult.DONE:
                self._done_workers.add(i)
        reports = [r for r in out if r.kind == TrainingResult.REPORT]
        if not reports and len(self._done_workers) == len(self.worker_group.workers):
            return None
        return reports or None

    def shutdown(self) -> None:
        if self.worker_group is not None:
            try:
                self._backend.on_shutdown(self.worker_group, self._backend_config)
            except Exception:
                pass
            self.worker_group.shutdown()
            self.worker_group = None
