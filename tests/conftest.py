"""Shared fixtures (modeled on the reference's conftest strategy,
reference: python/ray/tests/conftest.py ray_start_regular / ray_start_cluster).

JAX tests run on a virtual 8-device CPU mesh so multi-chip sharding logic is
exercised without TPU hardware (SURVEY §4 "fake TPU topology" note).
"""

import os

# The image's sitecustomize imports jax and pins the axon (real-TPU)
# platform before conftest runs, so plain env vars are too late; override
# through jax.config before any backend is initialized. Tests run on the
# deterministic 8-device virtual CPU mesh (SURVEY §4 fake-TPU-topology note).
#
# The tuning flags are FILTERED through a per-jaxlib probe first: jaxlib
# hard-aborts the whole pytest process on flags it doesn't know
# (parse_flags_from_env.cc FATAL), so a toolchain bump that drops e.g.
# the cpu-collective deadlines must degrade to "flag skipped", never to
# "suite SIGABRTs at the first jax computation".
import sys

# raylint R4's dynamic complement (ISSUE 7): the whole tier runs with
# asyncio debug mode on — task creation sites are recorded, cross-thread
# call_soon misuse raises instead of corrupting, and "coroutine ... was
# never awaited" warnings carry their origin. Python re-reads this env
# var at every event-loop creation, and the spawned daemons (gcs, agents,
# workers) inherit it, so coverage includes the server side. Set it
# before jax/asyncio load anything. Opt out (e.g. when profiling
# latency-sensitive benches under pytest) with RAY_TPU_ASYNCIO_DEBUG=0.
if os.environ.get("RAY_TPU_ASYNCIO_DEBUG", "1") != "0":
    os.environ["PYTHONASYNCIODEBUG"] = "1"
    # Marker for async_util's asyncio-logger mute (slow-callback WARNINGs
    # would corrupt pytest progress output); daemons inherit it. Scoped
    # to the harness so an app's own PYTHONASYNCIODEBUG stays untouched.
    os.environ["RAY_TPU_ASYNCIO_DEBUG_QUIET"] = "1"

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
from ray_tpu._private.xla_flags import (  # noqa: E402
    normalize_xla_flags, supported_xla_flags)

os.environ["XLA_FLAGS"] = normalize_xla_flags(" ".join(
    ([os.environ["XLA_FLAGS"]] if os.environ.get("XLA_FLAGS") else [])
    + supported_xla_flags([
        "--xla_force_host_platform_device_count=8",
        # XLA's in-process CPU collectives SIGABRT when a rendezvous
        # participant is >40s late; on a 1-core box running 8 virtual
        # devices the per-shard compute between collectives legitimately
        # starves threads past that (same rationale as __graft_entry__'s
        # _ensure_virtual_devices — correctness gate, not latency gate)
        "--xla_cpu_collective_call_terminate_timeout_seconds=1200",
        "--xla_cpu_collective_timeout_seconds=1200",
        "--xla_cpu_multi_thread_eigen=false",
        "intra_op_parallelism_threads=1",
    ])))

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402

# ---------------------------------------------------------------------------
# Test tiers. Files listed here form the `-m fast` smoke tier (< 5 min on a
# 1-CPU box, measured); everything else is `slow`. Individual tests inside a
# fast file can be pushed back to slow via SLOW_TESTS.
# ---------------------------------------------------------------------------
FAST_FILES = {
    "test_core_api.py",
    "test_actors.py",
    "test_kernel.py",
    "test_native_store.py",
    "test_streaming_generators.py",
    "test_memory_monitor.py",
    "test_serve_config.py",
    "test_autoscaler_v2.py",
    "test_state_api.py",
    "test_job_submission.py",
    "test_dashboard.py",
    "test_events_sql.py",
    "test_gke_rest.py",
    "test_runtime_env_container.py",
    "test_store_client.py",
    "test_accelerators.py",
    "test_cpp_client.py",
    "test_tune_bayesopt.py",
    "test_compiled_dag.py",
    "test_optional_adapters.py",
    "test_lifecycle.py",
    "test_transfer_plane.py",
    "test_partition.py",
    "test_actor_scale.py",
    "test_serve_load.py",
    "test_raylint.py",
    "test_sanitizer.py",
    "test_direct_call.py",
    "test_lineage.py",
    "test_data_shuffle.py",
    "test_flight_recorder.py",
    "test_memory_debugger.py",
    "test_checkpoint_manager.py",
    # elastic-training chaos suite: kill -9 mid-epoch + in-store resume
    # must stay on the smoke path (the rc-124 hang class it guards is
    # exactly the kind of regression that hides in the slow tier)
    "test_train_elastic.py",
    # in FAST so tier-1 exercises the gate (its standalone failure used
    # to hide behind the `-m 'not slow'` deselection — ISSUE 11)
    "test_dryrun_gate.py",
}
SLOW_TESTS: set = set()


def pytest_configure(config):
    # Promote "coroutine ... was never awaited" to an error (ISSUE 7
    # conftest hardening). The warning usually fires from the coroutine's
    # __del__ during GC, where a raised filter lands in the unraisable
    # hook — pytest's unraisableexception plugin rewraps it as a
    # PytestUnraisableExceptionWarning at the owning test, so the second
    # filter (message-scoped: other unraisable classes stay warnings) is
    # what actually fails the test. The first catches the rare sync-path
    # emission directly.
    # (?s): the rewrapped message is MULTI-LINE ("Exception ignored in:
    # ...\n\nTraceback ..."), and warnings filters re.match without
    # DOTALL — without the flag the second filter never fires.
    config.addinivalue_line(
        "filterwarnings",
        "error:(?s)coroutine .* was never awaited:RuntimeWarning")
    config.addinivalue_line(
        "filterwarnings",
        "error:(?s).*was never awaited:pytest.PytestUnraisableExceptionWarning")


def pytest_collection_modifyitems(config, items):
    for item in items:
        fname = os.path.basename(str(item.fspath))
        if fname in FAST_FILES and item.nodeid not in SLOW_TESTS:
            item.add_marker(pytest.mark.fast)
        else:
            item.add_marker(pytest.mark.slow)


# ---------------------------------------------------------------------------
# Sanitizer gate (ISSUE 19): when the suite runs under RAY_TPU_SANITIZE=1
# (test_sanitizer.py re-runs the kill -9 chaos test that way), any
# lock-order or affinity violation the runtime sanitizer recorded in
# THIS process fails the run at teardown. Off-knob runs never install
# the sanitizer, so the gate is a no-op bool check for the normal tier.
# ---------------------------------------------------------------------------
@pytest.fixture(scope="session", autouse=True)
def sanitizer_gate():
    yield
    from ray_tpu._private import sanitizer

    if sanitizer.ENABLED:
        sanitizer.assert_clean()


# ---------------------------------------------------------------------------
# Leak gate (ISSUE 1): any ray_tpu daemon or session dir that survives the
# whole run fails the suite — orphaned gcs/agent/forkserver processes and
# stale /dev/shm segments are exactly what starved the round-5 MULTICHIP
# gate. Everything found is also reaped so one leak can't poison the NEXT
# run. Disable with RAY_TPU_LEAK_CHECK=0 (e.g. when running a subset
# against an intentionally long-lived external cluster).
# ---------------------------------------------------------------------------
@pytest.fixture(scope="session", autouse=True)
def lifecycle_leak_gate():
    from ray_tpu._private import lifecycle

    baseline = {s["path"] for s in lifecycle.list_sessions()}
    yield
    import ray_tpu

    try:
        if ray_tpu.is_initialized():
            ray_tpu.shutdown()
    except Exception:
        pass
    if os.environ.get("RAY_TPU_LEAK_CHECK", "1") == "0":
        return  # disabled: report nothing, and never reap what may be a
        # deliberately long-lived external cluster
    # serving-plane stepper gate: a ContinuousBatchingEngine stepper
    # thread surviving the whole run means some engine was neither
    # drained (serve.shutdown → Replica.drain → engine.shutdown) nor
    # idle-expired — the exact daemon-leak class that turned the round-5
    # MULTICHIP gate red. Idle exit takes idle_timeout_s, so give the
    # threads a short window to wind down before calling it a leak.
    import sys as _sys
    import time as _time

    failures = []
    eng_mod = _sys.modules.get("ray_tpu.serve._private.engine")
    if eng_mod is not None:
        deadline = _time.monotonic() + 3.0
        steppers = eng_mod.live_stepper_threads()
        while steppers and _time.monotonic() < deadline:
            _time.sleep(0.1)
            steppers = eng_mod.live_stepper_threads()
        if steppers:
            failures.append(
                "continuous-batching engine stepper threads leaked past "
                "the end of the test run (engines must be shut down or "
                "left idle): " + ", ".join(steppers))
    # the session sweep must run even when the stepper gate failed — one
    # leak class must never shield another from being reaped
    leaked = [s for s in lifecycle.list_sessions()
              if s["path"] not in baseline]
    report = []
    for sess in leaked:
        live = ", ".join(
            f"{r.get('role', '?')}:{r['pid']}" for r in sess["live"])
        report.append(f"{sess['path']}"
                      + (f" [live: {live}]" if live else " [stale dir]"))
        lifecycle.reap_session(sess["path"], remove=True)
    if report:
        failures.append(
            "ray_tpu sessions leaked past the end of the test run "
            "(reaped now, but the teardown path that should have cleaned "
            "them is broken):\n  " + "\n  ".join(report))
    if failures:
        pytest.fail("\n".join(failures), pytrace=False)


# ---------------------------------------------------------------------------
# Object-ref leak gate (ISSUE 15): after each FAST-tier test, the driver
# worker's ownership ledger must be drained — a test that exits with
# owned objects, registered borrowers or task pins left behind is the
# exact leak shape the watchdog exists to catch in production, and the
# suite is where it is cheapest to find. Mirrors the session leak gate
# above. Opt out per test/module with @pytest.mark.ref_leaks_ok (for
# tests that intentionally hold refs past their end, e.g. module-scoped
# caches); disable wholesale with RAY_TPU_REF_LEAK_CHECK=0.
# ---------------------------------------------------------------------------
@pytest.fixture(autouse=True)
def object_ref_leak_gate(request):
    yield
    if os.environ.get("RAY_TPU_REF_LEAK_CHECK", "1") == "0":
        return
    if request.node.get_closest_marker("ref_leaks_ok") is not None:
        return
    if request.node.get_closest_marker("fast") is None:
        return  # slow tier: long e2e flows manage refs across tests
    import sys as _sys

    wm = _sys.modules.get("ray_tpu._private.worker")
    if wm is None:
        return
    w = wm.global_worker
    if w is None or not w.connected or w.mode != w.MODE_DRIVER:
        return
    import gc as _gc
    import time as _time

    rc = w.reference_counter

    def leaked():
        with rc._lock:
            owned = {b: m for b, m in rc._owned.items()
                     if m.state != "freed"}
            return owned, dict(rc._borrows), dict(rc._task_pins)

    # refs die via ObjectRef.__del__ → remove_local_ref, and borrow /
    # pin releases ride async RPCs: collect + give the plumbing a
    # bounded window to settle before calling anything a leak
    deadline = _time.monotonic() + 2.0
    _gc.collect()
    owned, borrows, pins = leaked()
    while (owned or borrows or pins) and _time.monotonic() < deadline:
        _time.sleep(0.05)
        _gc.collect()
        owned, borrows, pins = leaked()
    if not (owned or borrows or pins):
        return
    lines = []
    for b, meta in list(owned.items())[:20]:
        lines.append(
            f"  owned {b.hex()[:16]} state={meta.state} "
            f"size={meta.size} creator={meta.creator or '?'} "
            f"callsite={meta.callsite or '?'}")
    for b, n in list(borrows.items())[:10]:
        lines.append(f"  borrowers {b.hex()[:16]} count={n}")
    for b, n in list(pins.items())[:10]:
        lines.append(f"  task-pin {b.hex()[:16]} count={n}")
    pytest.fail(
        f"object refs leaked past the end of the test "
        f"({len(owned)} owned / {len(borrows)} borrowed / "
        f"{len(pins)} task-pinned). Drop the refs (or mark the test "
        f"ref_leaks_ok with justification):\n" + "\n".join(lines),
        pytrace=False)


@pytest.fixture(scope="module")
def ray_start_regular():
    import ray_tpu

    if not ray_tpu.is_initialized():
        ray_tpu.init(num_cpus=4)
    yield
    ray_tpu.shutdown()


@pytest.fixture
def ray_start_cluster():
    from ray_tpu.cluster_utils import Cluster

    cluster = Cluster(initialize_head=True, head_node_args={"num_cpus": 2})
    yield cluster
    import ray_tpu

    ray_tpu.shutdown()
    cluster.shutdown()


@pytest.fixture(scope="module")
def ray_cluster_2():
    """Two-node cluster (head + 1 worker), driver attached."""
    import ray_tpu
    from ray_tpu.cluster_utils import Cluster

    cluster = Cluster(initialize_head=True, head_node_args={"num_cpus": 4})
    cluster.add_node(num_cpus=4)
    ray_tpu.init(_node=cluster.head_node)
    cluster.wait_for_nodes()
    yield cluster
    ray_tpu.shutdown()
    cluster.shutdown()


@pytest.fixture(scope="module")
def ray_label_cluster():
    """Head (role=head) + worker (role=worker) for label scheduling tests."""
    import ray_tpu
    from ray_tpu.cluster_utils import Cluster

    cluster = Cluster(initialize_head=True,
                      head_node_args={"num_cpus": 2,
                                      "labels": {"role": "head"}})
    cluster.add_node(num_cpus=2, labels={"role": "worker"})
    ray_tpu.init(_node=cluster.head_node)
    cluster.wait_for_nodes()
    yield cluster
    ray_tpu.shutdown()
    cluster.shutdown()
