"""Aggregations (reference: python/ray/data/aggregate.py — AggregateFn with
Count/Sum/Min/Max/Mean/Std/AbsMax).

Two protocols:
- grouped: ``apply(group_dict, col_values) -> scalar`` per group;
- global: ``partial(block_dict) -> partial_state`` per block, then
  ``finalize(partials) -> scalar`` (distributive / algebraic aggregation).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

import numpy as np


class AggregateFn:
    agg_name = "agg"

    def __init__(self, on: Optional[str] = None, alias_name: Optional[str] = None):
        self.on = on
        self.alias = alias_name

    def output_name(self, key: Optional[str]) -> str:
        if self.alias:
            return self.alias
        return f"{self.agg_name}({self.on})" if self.on else f"{self.agg_name}()"

    # grouped path
    def apply(self, group: Dict[str, np.ndarray],
              col: Optional[np.ndarray]) -> Any:
        raise NotImplementedError

    # global path
    def partial(self, block: Dict[str, np.ndarray]) -> Any:
        raise NotImplementedError

    def finalize(self, partials: List[Any]) -> Any:
        raise NotImplementedError


class Count(AggregateFn):
    agg_name = "count"

    def apply(self, group, col):
        return len(next(iter(group.values()))) if group else 0

    def partial(self, block):
        return len(next(iter(block.values()))) if block else 0

    def finalize(self, partials):
        return int(sum(partials))


class Sum(AggregateFn):
    agg_name = "sum"

    def apply(self, group, col):
        return col.sum()

    def partial(self, block):
        return block[self.on].sum()

    def finalize(self, partials):
        return np.sum(partials)


class Min(AggregateFn):
    agg_name = "min"

    def apply(self, group, col):
        return col.min()

    def partial(self, block):
        v = block[self.on]
        return v.min() if len(v) else np.inf

    def finalize(self, partials):
        return np.min(partials)


class Max(AggregateFn):
    agg_name = "max"

    def apply(self, group, col):
        return col.max()

    def partial(self, block):
        v = block[self.on]
        return v.max() if len(v) else -np.inf

    def finalize(self, partials):
        return np.max(partials)


class Mean(AggregateFn):
    agg_name = "mean"

    def apply(self, group, col):
        return col.mean()

    def partial(self, block):
        v = block[self.on]
        return (v.sum(), len(v))

    def finalize(self, partials):
        total = sum(p[0] for p in partials)
        n = sum(p[1] for p in partials)
        return total / n if n else float("nan")


class Std(AggregateFn):
    agg_name = "std"

    def __init__(self, on=None, ddof: int = 1, alias_name=None):
        super().__init__(on, alias_name)
        self.ddof = ddof

    def apply(self, group, col):
        return col.std(ddof=self.ddof)

    def partial(self, block):
        v = block[self.on].astype(np.float64)
        return (v.sum(), (v * v).sum(), len(v))

    def finalize(self, partials):
        s = sum(p[0] for p in partials)
        s2 = sum(p[1] for p in partials)
        n = sum(p[2] for p in partials)
        if n - self.ddof <= 0:
            return float("nan")
        var = (s2 - s * s / n) / (n - self.ddof)
        return float(np.sqrt(max(var, 0.0)))


class AbsMax(AggregateFn):
    agg_name = "abs_max"

    def apply(self, group, col):
        return np.abs(col).max()

    def partial(self, block):
        v = block[self.on]
        return np.abs(v).max() if len(v) else 0

    def finalize(self, partials):
        return np.max(partials)
