"""Remaining accelerator families (reference:
python/ray/_private/accelerators/{amd_gpu,intel_gpu,neuron,hpu,npu}.py) —
real device-node/sysfs probing plus the standard visibility env vars, so
clusters mixing hardware advertise the same custom resources the
reference does.

Detection per family (all probe-able offline, no vendor SDK needed):
AMD via kfd topology gpu_ids, Intel via DRM render nodes with the 8086
vendor id, Neuron via /dev/neuron* (2 cores per device, the reference's
neuron-ls accounting), Habana via /dev/accel* whose driver symlink says
habana (shared namespace with TPU accel nodes — the driver name is the
discriminator), Ascend NPU via /dev/davinci*. An explicit
``RAY_TPU_NUM_*`` env var always wins (containers without sysfs; tests).
"""

from __future__ import annotations

import glob
import os
import re
from typing import Dict, List

from ray_tpu._private.accelerators.accelerator import AcceleratorManager


def _env_count(var: str) -> int:
    try:
        return int(os.environ.get(var, "-1"))
    except ValueError:
        return -1


class _ProbingManager(AcceleratorManager):
    RESOURCE = ""
    VISIBLE_ENV = ""
    COUNT_ENV = ""
    # overridable roots so tests can point at a fake /sys and /dev tree
    SYS_ROOT = "/sys"
    DEV_ROOT = "/dev"

    @classmethod
    def get_resource_name(cls) -> str:
        return cls.RESOURCE

    @classmethod
    def get_visible_accelerator_ids_env_var(cls) -> str:
        return cls.VISIBLE_ENV

    @classmethod
    def _detect(cls) -> int:
        return 0

    @classmethod
    def get_current_node_num_accelerators(cls) -> int:
        override = _env_count(cls.COUNT_ENV)
        if override >= 0:
            return override
        try:
            return cls._detect()
        except OSError:
            return 0

    @classmethod
    def set_visible_accelerator_ids(cls, ids: List[int]) -> None:
        os.environ[cls.VISIBLE_ENV] = ",".join(str(i) for i in ids)

    @classmethod
    def get_current_node_additional_resources(cls) -> Dict[str, float]:
        return {}


class AMDGPUAcceleratorManager(_ProbingManager):
    """reference: accelerators/amd_gpu.py (HIP_VISIBLE_DEVICES). kfd
    topology lists CPUs too; only nodes with a nonzero gpu_id are GPUs."""

    RESOURCE = "GPU"
    VISIBLE_ENV = "HIP_VISIBLE_DEVICES"
    COUNT_ENV = "RAY_TPU_NUM_AMD_GPUS"

    @classmethod
    def _detect(cls) -> int:
        count = 0
        for path in glob.glob(os.path.join(
                cls.SYS_ROOT, "class/kfd/kfd/topology/nodes/*/gpu_id")):
            try:
                with open(path) as f:
                    if f.read().strip() not in ("", "0"):
                        count += 1
            except OSError:
                pass
        return count


class IntelGPUAcceleratorManager(_ProbingManager):
    """reference: accelerators/intel_gpu.py (ONEAPI_DEVICE_SELECTOR).
    DRM render nodes whose PCI vendor is 0x8086."""

    RESOURCE = "GPU"
    VISIBLE_ENV = "ONEAPI_DEVICE_SELECTOR"
    COUNT_ENV = "RAY_TPU_NUM_INTEL_GPUS"

    @classmethod
    def _detect(cls) -> int:
        count = 0
        for node in glob.glob(os.path.join(
                cls.SYS_ROOT, "class/drm/renderD*")):
            try:
                with open(os.path.join(node, "device/vendor")) as f:
                    if f.read().strip().lower() != "0x8086":
                        continue
                # skip the boot display (integrated graphics): an iGPU on
                # a CPU node must not advertise a schedulable GPU
                try:
                    with open(os.path.join(node,
                                           "device/boot_vga")) as f:
                        if f.read().strip() == "1":
                            continue
                except OSError:
                    pass  # discrete/headless parts often omit the file
                count += 1
            except OSError:
                pass
        return count


class NeuronAcceleratorManager(_ProbingManager):
    """reference: accelerators/neuron.py (NEURON_RT_VISIBLE_CORES);
    inf/trn devices appear as /dev/neuron<N>, two NeuronCores each."""

    RESOURCE = "neuron_cores"
    VISIBLE_ENV = "NEURON_RT_VISIBLE_CORES"
    COUNT_ENV = "RAY_TPU_NUM_NEURON_CORES"
    CORES_PER_DEVICE = 2

    @classmethod
    def _detect(cls) -> int:
        devices = [p for p in glob.glob(os.path.join(cls.DEV_ROOT,
                                                     "neuron*"))
                   if re.fullmatch(r"neuron\d+", os.path.basename(p))]
        return len(devices) * cls.CORES_PER_DEVICE


class HPUAcceleratorManager(_ProbingManager):
    """reference: accelerators/hpu.py (HABANA_VISIBLE_MODULES). Gaudi
    shares the /dev/accel* namespace with TPUs; the sysfs driver symlink
    (habanalabs) is the discriminator."""

    RESOURCE = "HPU"
    VISIBLE_ENV = "HABANA_VISIBLE_MODULES"
    COUNT_ENV = "RAY_TPU_NUM_HPUS"

    @classmethod
    def _detect(cls) -> int:
        count = 0
        for node in glob.glob(os.path.join(cls.SYS_ROOT,
                                           "class/accel/accel*")):
            driver = os.path.join(node, "device/driver")
            try:
                if "habana" in os.path.basename(
                        os.readlink(driver)).lower():
                    count += 1
            except OSError:
                pass
        return count


class NPUAcceleratorManager(_ProbingManager):
    """reference: accelerators/npu.py (ASCEND_RT_VISIBLE_DEVICES);
    Ascend devices appear as /dev/davinci<N>."""

    RESOURCE = "NPU"
    VISIBLE_ENV = "ASCEND_RT_VISIBLE_DEVICES"
    COUNT_ENV = "RAY_TPU_NUM_NPUS"

    @classmethod
    def _detect(cls) -> int:
        return len([p for p in glob.glob(os.path.join(cls.DEV_ROOT,
                                                      "davinci*"))
                    if re.fullmatch(r"davinci\d+", os.path.basename(p))])
