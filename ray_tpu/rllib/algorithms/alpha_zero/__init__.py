from ray_tpu.rllib.algorithms.alpha_zero.alpha_zero import (
    MCTS, AlphaZero, AlphaZeroConfig)

__all__ = ["AlphaZero", "AlphaZeroConfig", "MCTS"]
