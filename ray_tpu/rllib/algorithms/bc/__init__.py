from ray_tpu.rllib.algorithms.bc.bc import BC, BCConfig

__all__ = ["BC", "BCConfig"]
