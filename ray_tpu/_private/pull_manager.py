"""Cross-node pull pipeline (reference: ``object_manager.h:117`` windowed
Push/Pull chunking + ``pull_manager.h`` admission control).

One transfer = one object moving into the local store. The manager keeps
``object_pull_window`` chunk requests in flight per holder connection
(throughput ``window * chunk / RTT`` instead of ``chunk / RTT``), stripes
the chunk range across every advertised holder (each live holder's window
workers pop the shared chunk deque, so striping load-balances by actual
service rate), writes every reply into the pre-created store view at its
offset (offsets are disjoint, so out-of-order completion is safe), and
fails a dead holder's in-flight chunks over to the survivors by pushing
them back onto the deque.

Admission: a node-wide FIFO byte budget caps unsealed pull allocations so
a burst of large gets cannot blow past store capacity; queued transfers
admit in arrival order as in-flight bytes retire.

Bulk chunk frames ride a dedicated per-peer data channel
(``ConnectionPool.get(..., kind="data")``) so a 1 GB transfer never
head-of-line-blocks lease/wait control frames to the same peer.
"""

from __future__ import annotations

import asyncio
import time
from collections import deque
from typing import Dict, List, Optional, Tuple

from ray_tpu._private.broadcast import TransferProgress
from ray_tpu._private.config import CONFIG
from ray_tpu._private.ids import ObjectID


class PullBudget:
    """FIFO byte-budget admission (reference: pull_manager.h's
    NumBytesBeingPulled cap). An oversized transfer (> limit) admits alone
    once the pipe is empty, so a single huge object can always move."""

    def __init__(self, limit: int):
        self.limit = max(1, int(limit))
        self.inflight = 0
        self._waiters: deque = deque()  # (size, future) in arrival order
        self.queued_total = 0  # transfers that had to wait at least once

    def _admissible(self, size: int) -> bool:
        return self.inflight == 0 or self.inflight + size <= self.limit

    async def acquire(self, size: int) -> None:
        if not self._waiters and self._admissible(size):
            self.inflight += size
            return
        fut = asyncio.get_running_loop().create_future()
        self._waiters.append((size, fut))
        self.queued_total += 1
        try:
            await fut
        except asyncio.CancelledError:
            if fut.done() and not fut.cancelled():
                # admitted in the same tick we were cancelled: give back
                self.release(size)
            else:
                try:
                    self._waiters.remove((size, fut))
                except ValueError:
                    pass
            raise

    def release(self, size: int) -> None:
        self.inflight = max(0, self.inflight - size)
        while self._waiters:
            size_next, fut = self._waiters[0]
            if fut.done():  # cancelled while queued
                self._waiters.popleft()
                continue
            if not self._admissible(size_next):
                break
            self._waiters.popleft()
            self.inflight += size_next
            fut.set_result(True)

    @property
    def queued(self) -> int:
        return len(self._waiters)


class PullManager:
    """Executes one object transfer at wire speed; owned by the node agent.

    The agent keeps the pull *policy* (locate rounds, deadlines, lineage
    verdicts); this class keeps the *mechanism* (windows, stripes,
    budget, counters).
    """

    def __init__(self, agent):
        self.agent = agent
        cap = CONFIG.object_pull_max_inflight_bytes
        if not cap:
            cap = max(agent.store.capacity // 4,
                      CONFIG.object_chunk_size_bytes)
        self.budget = PullBudget(cap)
        # in-flight transfer progress, keyed by object hex: the relay
        # source for broadcast-tree children (agent._fetch_object_chunk
        # serves partially-received ranges straight out of these)
        self.active: Dict[str, TransferProgress] = {}
        # hot-path counters, exported via GetPullStats + node gauges
        self.window_occupancy = 0  # chunk RPCs in flight right now
        self.window_occupancy_peak = 0
        self.transfers_concurrent = 0   # transfers inside _transfer now
        self.transfers_concurrent_peak = 0
        self.chunks_fetched = 0
        self.bytes_fetched = 0
        self.transfers_ok = 0
        self.transfers_failed = 0
        self.stripe_failovers = 0
        self.pulls_cancelled = 0
        self.peer_removed_failfasts = 0  # node-death verdicts applied
        self.transfer_seconds = 0.0  # time inside _transfer (ok ones)
        # broadcast-tree counters (device object plane, ISSUE 9)
        self.bcast_joins = 0            # tree slots taken (incl. re-joins)
        self.bcast_tree_pulls = 0       # objects sealed via a tree parent
        self.bcast_reparents_client = 0  # dead parents this node reported
        self.bcast_fallbacks = 0        # tree pulls degraded to striped
        self.bcast_last_depth = 0       # depth of the latest tree slot
        self.bcast_relay_chunks = 0     # chunks served from unsealed views
        self.bcast_relay_bytes = 0

    def on_peer_removed(self, addr: Dict) -> None:
        """A cluster-level death verdict for a holder peer: drop BOTH its
        channels so every in-flight chunk RPC to it fails immediately
        (ConnectionLost on the pending futures) and the stripes fail the
        dead holder's chunks over to survivors — the fail-fast path for
        partitions, where the socket itself would stay silently open
        until the 60 s chunk deadline."""
        self.peer_removed_failfasts += 1
        self.agent.pool.drop(addr["host"], addr["port"])

    def stats(self) -> Dict:
        return {
            "window_occupancy": self.window_occupancy,
            "window_occupancy_peak": self.window_occupancy_peak,
            "transfers_concurrent": self.transfers_concurrent,
            "transfers_concurrent_peak": self.transfers_concurrent_peak,
            "chunks_fetched": self.chunks_fetched,
            "bytes_fetched": self.bytes_fetched,
            "transfers_ok": self.transfers_ok,
            "transfers_failed": self.transfers_failed,
            "stripe_failovers": self.stripe_failovers,
            "pulls_cancelled": self.pulls_cancelled,
            "peer_removed_failfasts": self.peer_removed_failfasts,
            "inflight_bytes": self.budget.inflight,
            "budget_limit_bytes": self.budget.limit,
            "pulls_queued": self.budget.queued,
            "pulls_queued_total": self.budget.queued_total,
            "transfer_seconds": round(self.transfer_seconds, 4),
            "bcast_joins": self.bcast_joins,
            "bcast_tree_pulls": self.bcast_tree_pulls,
            "bcast_reparents": self.bcast_reparents_client,
            "bcast_fallbacks": self.bcast_fallbacks,
            "bcast_tree_depth": self.bcast_last_depth,
            "bcast_relay_chunks": self.bcast_relay_chunks,
            "bcast_relay_bytes": self.bcast_relay_bytes,
            "transfers_active": len(self.active),
        }

    # ------------------------------------------- relay progress registry
    def register_progress(self, hex_id: str, size: int) -> TransferProgress:
        """Announce an upcoming pull so broadcast children assigned to
        this node park on its progress (through admission delay and
        retries) instead of bouncing off an absent verdict."""
        prog = TransferProgress(hex_id, size)
        self.active[hex_id] = prog
        return prog

    def unregister_progress(self, hex_id: str,
                            prog: TransferProgress) -> None:
        if self.active.get(hex_id) is prog:
            self.active.pop(hex_id, None)
        # wake parked relay serves; each re-checks the (possibly just
        # sealed) store before answering absent
        prog.fail()

    # ------------------------------------------------------------- transfer
    async def fetch(self, hex_id: str, holders: List[Dict], *,
                    meta: Optional[Tuple] = None,
                    progress: Optional[TransferProgress] = None) -> str:
        """Pull one object from `holders` into the local store.

        Returns 'ok' | 'absent' (some holder alive, object not there) |
        'conn' (every holder unreachable) | 'local' (local store error).
        Only 'conn' feeds the agent's dead-holder fast-fail.

        ``meta=(size, alive_holders, saw_absent)`` skips the probe round
        (broadcast pulls already know the size and their single parent —
        probing a mid-relay parent would misread its unsealed state).
        ``progress`` tracks received byte ranges for chunk-level relay.
        """
        if meta is not None:
            size, alive, any_absent = meta
        else:
            size, alive, any_absent = await self._probe_meta(hex_id, holders)
        if size is None:
            return "absent" if any_absent else "conn"
        await self.budget.acquire(size)
        t0 = time.monotonic()
        self.transfers_concurrent += 1
        self.transfers_concurrent_peak = max(
            self.transfers_concurrent_peak, self.transfers_concurrent)
        try:
            status = await self._transfer(hex_id, size, alive,
                                          progress=progress)
        finally:
            self.transfers_concurrent -= 1
            self.budget.release(size)
        if status == "ok":
            self.transfers_ok += 1
            self.transfer_seconds += time.monotonic() - t0
            # the holders we fetched from keep sealed copies: record them
            # as remote-tier restore sources for this object
            self.agent.store.note_remote_source(hex_id, alive)
        else:
            self.transfers_failed += 1
        return status

    async def _probe_meta(self, hex_id: str, holders: List[Dict]
                          ) -> Tuple[Optional[int], List[Dict], bool]:
        """Ask every holder (control channel, CONCURRENTLY — a dead
        holder's connect timeout must not stall the probe of the live
        ones) which of them has the object; returns (size, holders that
        have it, saw_absent)."""

        async def probe(addr: Dict):
            client = None
            try:
                client = await self.agent.pool.get(addr["host"], addr["port"])
                return await client.call(
                    "FetchObjectMeta", {"object_id": hex_id},
                    timeout=CONFIG.object_locate_timeout_s)
            except asyncio.CancelledError:
                raise
            except Exception:
                # drop the ctrl channel only when it is actually broken —
                # a reply timeout on a busy-but-alive peer must not fail
                # that peer's unrelated in-flight control RPCs (and never
                # touch its data channel mid-transfer)
                if client is None or not client.connected:
                    self.agent.pool.drop(addr["host"], addr["port"],
                                         kind="ctrl")
                return None  # treated as not-a-holder this round

        metas = await asyncio.gather(*[probe(a) for a in holders])
        size: Optional[int] = None
        alive: List[Dict] = []
        any_absent = False
        for addr, meta in zip(holders, metas):
            if meta and meta.get("exists"):
                if meta.get("partial"):
                    # mid-pull relay source: not stripe-able by the plain
                    # path (its unsealed ranges arrive on ITS schedule);
                    # count as absent-this-round so the locate loop
                    # retries after the holder seals
                    any_absent = True
                    continue
                alive.append(addr)
                if size is None:
                    size = meta["size"]
            elif meta is not None:
                any_absent = True
        return size, alive, any_absent

    async def _transfer(self, hex_id: str, size: int,
                        holders: List[Dict],
                        progress: Optional[TransferProgress] = None) -> str:
        oid = ObjectID.from_hex(hex_id)
        try:
            view, handle = self.agent.store.client.create(oid, size)
        except Exception:
            return "local"
        if progress is not None:
            # re-arm (retries allocate a fresh view; marks from an
            # aborted attempt describe freed memory)
            progress.reset(view)
        chunk = max(1, CONFIG.object_chunk_size_bytes)
        todo: deque = deque(range(0, size, chunk))
        total_chunks = len(todo) or 1
        bytes_done = [0]  # list: closed over by the stripe workers
        window = max(1, CONFIG.object_pull_window)

        async def holder_stripe(addr: Dict) -> str:
            """All window workers for one holder; returns that holder's
            terminal status ('ok' even if it fetched nothing)."""
            try:
                client = await self.agent.pool.get(
                    addr["host"], addr["port"], kind="data")
            except Exception:
                self.agent.pool.drop(addr["host"], addr["port"])
                return "conn"

            failed = [None]  # first failure on this holder, stops its window

            async def worker() -> None:
                while todo and failed[0] is None:
                    off = todo.popleft()
                    # clamp to the owning chunk's end: a truncated-reply
                    # requeue lands mid-chunk and must not overlap the
                    # next chunk's range (double write + double count)
                    n = min(chunk - off % chunk, size - off)
                    self.window_occupancy += 1
                    self.window_occupancy_peak = max(
                        self.window_occupancy_peak, self.window_occupancy)
                    try:
                        # raw reply streams straight into the store view at
                        # this chunk's offset; out-of-order completion is
                        # safe because offsets are disjoint
                        got = await client.call_raw_into(
                            "FetchObjectChunk",
                            {"object_id": hex_id, "offset": off,
                             "length": n},
                            view[off:off + n],
                            timeout=CONFIG.object_chunk_fetch_timeout_s)
                    except Exception:
                        # connection-level failure: hand the chunk to a
                        # surviving holder's window and stop this stripe
                        todo.appendleft(off)
                        failed[0] = "conn"
                        self.stripe_failovers += 1
                        return
                    finally:
                        self.window_occupancy -= 1
                    if got is None or (got == 0 and n > 0):
                        # holder alive but object evicted / its view is
                        # shorter than the advertised size — a 0-byte
                        # reply must NOT requeue-and-retry the same
                        # offset in a tight loop
                        todo.appendleft(off)
                        failed[0] = "absent"
                        return
                    bytes_done[0] += got
                    self.chunks_fetched += 1
                    self.bytes_fetched += got
                    if progress is not None and got > 0:
                        progress.mark(off, got)  # relay children may now
                        # stream this range while the rest arrives
                    if got < n:  # truncated reply: refetch the rest
                        todo.append(off + got)

            workers = [asyncio.ensure_future(worker())
                       for _ in range(min(window, total_chunks))]
            try:
                await asyncio.gather(*workers)
            except asyncio.CancelledError:
                for w in workers:
                    w.cancel()
                await asyncio.gather(*workers, return_exceptions=True)
                raise
            if failed[0] == "conn":
                # invalidate only the DATA channel: a chunk timeout on an
                # overloaded-but-alive holder must not fail the peer's
                # unrelated in-flight control RPCs (lease/wait/locate)
                self.agent.pool.drop(addr["host"], addr["port"], kind="data")
            return failed[0] or "ok"

        saw_absent = False
        stripes = [asyncio.ensure_future(holder_stripe(a)) for a in holders]
        try:
            live = list(holders)
            statuses = await asyncio.gather(*stripes)
            # survivors may have finished while a dead holder's chunks were
            # still being requeued; drain leftovers through every holder
            # that ended clean
            while todo and any(st == "ok" for st in statuses):
                saw_absent = saw_absent or "absent" in statuses
                live = [a for a, st in zip(live, statuses) if st == "ok"]
                stripes = [asyncio.ensure_future(holder_stripe(a))
                           for a in live]
                statuses = await asyncio.gather(*stripes)
            saw_absent = saw_absent or "absent" in statuses
        except asyncio.CancelledError:
            self.pulls_cancelled += 1
            for s in stripes:
                s.cancel()
            await asyncio.gather(*stripes, return_exceptions=True)
            if progress is not None:
                progress.fail()  # before abort: relay serves must never
                # slice a closed mmap
            self.agent.store.client.abort(handle)
            raise
        if bytes_done[0] >= size and not todo:
            try:
                self.agent.store.client.seal(oid, handle)
            except Exception:
                if progress is not None:
                    progress.fail()
                self.agent.store.client.abort(handle)
                return "local"
            self.agent.store.on_sealed(hex_id, size)
            return "ok"
        if progress is not None:
            progress.fail()
        self.agent.store.client.abort(handle)
        return "absent" if saw_absent else "conn"
