"""TuneController — the experiment event loop (reference:
python/ray/tune/execution/tune_controller.py:72 — step :718, actor
scheduling :1016, train :1522, save :1743, restore :1844).

One trial = one ``_TrialActor`` scheduled through the normal actor path
with the trial's resource request; trainers launched inside a trial
reserve their own worker-group placement groups
(ray_tpu.train.DataParallelTrainer._reserve_placement_group), so the trial
actor itself stays lightweight.
"""

from __future__ import annotations

import json
import os
import pickle
import time
import uuid
from typing import Dict, List, Optional

import ray_tpu
from ray_tpu.air.config import CheckpointConfig, FailureConfig, RunConfig
from ray_tpu.tune.experiment import Trial
from ray_tpu.tune.schedulers.trial_scheduler import (
    FIFOScheduler, TrialScheduler)
from ray_tpu.tune.search.searcher import Searcher


class _TrialActor:
    """Remote wrapper hosting one Trainable instance."""

    def __init__(self, trainable_cls, config, trial_id, trial_dir):
        self._trainable = trainable_cls(
            config=config, trial_id=trial_id, trial_dir=trial_dir)

    def train(self):
        return self._trainable.train()

    def save(self):
        return self._trainable.save()

    def restore(self, checkpoint_dir):
        self._trainable.restore(checkpoint_dir)
        return True

    def stop(self):
        self._trainable.stop()
        return True


class TuneController:
    def __init__(
        self,
        trainable_cls,
        *,
        experiment_dir: str,
        search_alg: Searcher,
        scheduler: Optional[TrialScheduler] = None,
        metric: Optional[str] = None,
        mode: str = "max",
        num_samples_cap: Optional[int] = None,
        max_concurrent: int = 8,
        time_budget_s: Optional[float] = None,
        run_config: Optional[RunConfig] = None,
        resources_per_trial: Optional[Dict[str, float]] = None,
        sync_uri: Optional[str] = None,
    ):
        self._trainable_cls = trainable_cls
        self.experiment_dir = experiment_dir
        # remote persistence: experiment state + trial checkpoints run in a
        # local working dir and mirror to this URI on every state save
        # (reference: pyarrow-fs experiment sync,
        # train/_internal/storage.py:99-111)
        self._sync_uri = sync_uri
        os.makedirs(experiment_dir, exist_ok=True)
        self.search_alg = search_alg
        self.scheduler = scheduler or FIFOScheduler()
        self.metric = metric
        self.mode = mode
        self.search_alg.set_search_properties(metric, mode, None)
        self.scheduler.set_search_properties(metric, mode)
        self.num_samples_cap = num_samples_cap
        self.max_concurrent = max_concurrent
        self.time_budget_s = time_budget_s
        self.run_config = run_config or RunConfig()
        self.failure_config = self.run_config.failure_config or FailureConfig()
        self.checkpoint_config = (self.run_config.checkpoint_config
                                  or CheckpointConfig())
        self.resources_per_trial = resources_per_trial or {"CPU": 1.0}

        # callbacks: default file loggers + user callbacks (reference:
        # tune/logger — CSV/JSON written for every trial by default)
        from ray_tpu.tune.logger import (
            CSVLoggerCallback, JsonLoggerCallback)

        self.callbacks = [JsonLoggerCallback(), CSVLoggerCallback()]
        self.callbacks.extend(self.run_config.callbacks or [])
        self._iteration = 0

        self.trials: List[Trial] = []
        self._actors: Dict[str, object] = {}       # trial_id -> ActorHandle
        self._inflight: Dict[object, Trial] = {}   # train() ref -> trial
        self._searcher_done = False
        self._ckpt_requests: set = set()
        self._last_state_save = 0.0

    # --------------------------------------------------- scheduler interface
    def live_trials(self) -> List[Trial]:
        return [t for t in self.trials if not t.is_finished]

    def trial_checkpoint(self, trial: Trial) -> Optional[str]:
        """Synchronously checkpoint a (running) trial; used by PBT exploit."""
        actor = self._actors.get(trial.trial_id)
        if actor is None:
            return trial.checkpoint_path
        try:
            path = ray_tpu.get(actor.save.remote(), timeout=120)
            trial.checkpoint_path = path
            return path
        except Exception:
            return trial.checkpoint_path

    def request_checkpoint(self, trial: Trial) -> None:
        self._ckpt_requests.add(trial.trial_id)

    # ------------------------------------------------------------- main loop
    def run(self) -> List[Trial]:
        start = time.monotonic()
        while True:
            self._maybe_create_trials()
            self._maybe_start_trials()
            if not self._inflight:
                if all(t.is_finished for t in self.trials) and (
                        self._searcher_done or self._reached_sample_cap()):
                    break
                if not self.live_trials() and self._searcher_done:
                    break
                time.sleep(0.01)
                continue
            ready, _ = ray_tpu.wait(
                list(self._inflight.keys()), num_returns=1, timeout=1.0)
            for ref in ready:
                trial = self._inflight.pop(ref)
                self._process_result(trial, ref)
            if self.time_budget_s is not None and \
                    time.monotonic() - start > self.time_budget_s:
                self._stop_all("time budget exhausted")
                break
            from ray_tpu.tune.stopper import Stopper

            if isinstance(self.run_config.stop, Stopper) and \
                    self.run_config.stop.stop_all():
                self._stop_all("stopper.stop_all()")
                break
            self._maybe_save_state()
        self._save_state()
        for cb in self.callbacks:
            try:
                cb.on_experiment_end(self.trials)
            except Exception:
                pass
        return self.trials

    def _fire(self, hook: str, trial, *args) -> None:
        self._iteration += 1
        for cb in self.callbacks:
            try:
                getattr(cb, hook)(self._iteration, self.trials, trial,
                                  *args)
            except Exception:
                pass

    def _reached_sample_cap(self) -> bool:
        return (self.num_samples_cap is not None
                and len(self.trials) >= self.num_samples_cap)

    # ------------------------------------------------------- trial lifecycle
    def _maybe_create_trials(self) -> None:
        while not self._searcher_done and not self._reached_sample_cap() \
                and len(self.live_trials()) < self.max_concurrent:
            tid = uuid.uuid4().hex[:8]
            cfg = self.search_alg.suggest(tid)
            if cfg == Searcher.FINISHED:
                self._searcher_done = True
                return
            if cfg is None:
                return
            trial = Trial(cfg, self.experiment_dir, trial_id=tid,
                          resources=dict(self.resources_per_trial))
            self.trials.append(trial)
            self.scheduler.on_trial_add(self, trial)

    def _maybe_start_trials(self) -> None:
        running = len(self._actors)
        may_resume = getattr(self.scheduler, "may_resume", None)
        for trial in self.trials:
            if running >= self.max_concurrent:
                return
            if trial.status in (Trial.PENDING, Trial.PAUSED) and \
                    trial.trial_id not in self._actors:
                # scheduler hold (sync HyperBand rung barrier)
                if trial.status == Trial.PAUSED and may_resume is not None \
                        and not may_resume(trial):
                    continue
                self._start_trial(trial)
                running += 1

    def _start_trial(self, trial: Trial) -> None:
        actor = ray_tpu.remote(_TrialActor).options(
            resources=trial.resources).remote(
                self._trainable_cls, trial.config, trial.trial_id,
                trial.local_dir)
        self._actors[trial.trial_id] = actor
        try:
            if trial.restore_path:
                ray_tpu.get(actor.restore.remote(trial.restore_path),
                            timeout=300)
                trial.restore_path = None
        except Exception as e:
            self._handle_failure(trial, e)
            return
        trial.status = Trial.RUNNING
        self._fire("on_trial_start", trial)
        self._submit_train(trial)

    def _submit_train(self, trial: Trial) -> None:
        actor = self._actors[trial.trial_id]
        ref = actor.train.remote()
        self._inflight[ref] = trial

    def _teardown_actor(self, trial: Trial, graceful: bool = True) -> None:
        actor = self._actors.pop(trial.trial_id, None)
        if actor is None:
            return
        # drop any stale in-flight ref for this trial
        for ref, t in list(self._inflight.items()):
            if t is trial:
                del self._inflight[ref]
        if graceful:
            try:
                ray_tpu.get(actor.stop.remote(), timeout=30)
            except Exception:
                pass
        try:
            ray_tpu.kill(actor)
        except Exception:
            pass

    # ------------------------------------------------------ result handling
    def _process_result(self, trial: Trial, ref) -> None:
        try:
            result = ray_tpu.get(ref)
        except Exception as e:
            self._handle_failure(trial, e)
            return

        ckpt_dir = result.pop("_checkpoint_dir", None)
        if ckpt_dir:
            trial.checkpoint_path = ckpt_dir
        done = bool(result.get("done")) or self._hit_stop_criteria(result)
        if done:
            # a trial resumed at its end reports a bare done step; keep the
            # metrics it had already reached
            result = {**trial.last_result, **result}
        trial.last_result = result
        trial.metric_history.append(result)
        self._fire("on_trial_result", trial, result)

        if done:
            self._complete_trial(trial, result)
            return

        self.search_alg.on_trial_result(trial.trial_id, result)
        decision = self.scheduler.on_trial_result(self, trial, result)

        freq = self.checkpoint_config.checkpoint_frequency
        want_ckpt = (trial.trial_id in self._ckpt_requests or (
            freq and result.get("training_iteration", 0) % freq == 0))
        if want_ckpt:
            self._ckpt_requests.discard(trial.trial_id)
            self.trial_checkpoint(trial)

        if decision == TrialScheduler.CONTINUE:
            self._submit_train(trial)
        elif decision == TrialScheduler.PAUSE:
            self.trial_checkpoint(trial)
            trial.restore_path = trial.checkpoint_path
            self._teardown_actor(trial)
            trial.status = Trial.PAUSED
        elif decision == TrialScheduler.RESTART:
            # PBT exploit: trial.config/restore_path already mutated
            self._teardown_actor(trial)
            trial.status = Trial.PENDING
        elif decision == TrialScheduler.STOP:
            self._complete_trial(trial, result, early_stopped=True)
        else:
            raise ValueError(f"unknown scheduler decision {decision!r}")

    def _hit_stop_criteria(self, result: Dict) -> bool:
        stop = self.run_config.stop
        if not stop:
            return False
        if callable(stop):
            return bool(stop(result.get("trial_id"), result))
        return any(k in result and result[k] >= v for k, v in stop.items())

    def _complete_trial(self, trial: Trial, result: Dict,
                        early_stopped: bool = False) -> None:
        if self.checkpoint_config.checkpoint_frequency or \
                trial.trial_id in self._ckpt_requests:
            self.trial_checkpoint(trial)
            self._ckpt_requests.discard(trial.trial_id)
        self.scheduler.on_trial_complete(self, trial, result)
        self.search_alg.on_trial_complete(trial.trial_id, result, error=False)
        self._teardown_actor(trial)
        trial.status = Trial.TERMINATED
        self._fire("on_trial_complete", trial)

    def _handle_failure(self, trial: Trial, error: Exception) -> None:
        trial.num_failures += 1
        self._teardown_actor(trial, graceful=False)
        max_failures = self.failure_config.max_failures
        if not self.failure_config.fail_fast and (
                max_failures < 0 or trial.num_failures <= max_failures):
            # retry from the last checkpoint
            trial.restore_path = trial.checkpoint_path
            trial.status = Trial.PENDING
            return
        trial.status = Trial.ERROR
        trial.error_msg = f"{type(error).__name__}: {error}"
        self._fire("on_trial_error", trial)
        self.scheduler.on_trial_error(self, trial)
        self.search_alg.on_trial_complete(trial.trial_id, None, error=True)
        if self.failure_config.fail_fast:
            self._stop_all("fail_fast")

    def _stop_all(self, reason: str) -> None:
        for trial in self.live_trials():
            self._teardown_actor(trial)
            if trial.status in (Trial.RUNNING, Trial.PENDING, Trial.PAUSED):
                trial.status = Trial.TERMINATED
        self._inflight.clear()

    # ------------------------------------------------------ experiment state
    def _maybe_save_state(self) -> None:
        if time.monotonic() - self._last_state_save > 10:
            self._save_state()

    def _save_state(self) -> None:
        self._last_state_save = time.monotonic()
        state = {
            "timestamp": time.time(),
            "metric": self.metric,
            "mode": self.mode,
            "trials": [t.to_state() for t in self.trials],
        }
        path = os.path.join(self.experiment_dir, "experiment_state.json")
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(state, f, indent=1, default=str)
        os.replace(tmp, path)
        try:
            with open(os.path.join(self.experiment_dir,
                                   "searcher_state.pkl"), "wb") as f:
                f.write(self.search_alg.save_state())
        except Exception:
            pass
        if self._sync_uri:
            from ray_tpu._private.storage import get_storage_backend

            try:
                get_storage_backend(self._sync_uri).upload_dir(
                    self.experiment_dir, self._sync_uri)
            except Exception as e:  # keep training; surface in the log
                import logging

                logging.getLogger(__name__).warning(
                    "experiment sync to %s failed: %s", self._sync_uri, e)

    @staticmethod
    def load_state(experiment_dir: str) -> Optional[Dict]:
        path = os.path.join(experiment_dir, "experiment_state.json")
        if not os.path.exists(path):
            return None
        with open(path) as f:
            return json.load(f)
