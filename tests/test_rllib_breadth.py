"""Reward-gated tests for the round-3 algorithm families — ARS, CRR,
AlphaZero (reference: rllib/tuned_examples/ CI learning gates; VERDICT r2
missing #5). Same discipline as test_rllib_learning.py: tiny envs, minutes
on one CPU, and the algorithm must actually learn, not just run."""

import numpy as np
import pytest

import ray_tpu

try:
    import gymnasium as gym
except ImportError:  # pragma: no cover
    gym = None

pytestmark = pytest.mark.skipif(gym is None, reason="gymnasium required")


@pytest.fixture(scope="module")
def ray4():
    if not ray_tpu.is_initialized():
        ray_tpu.init(num_cpus=4)
    yield
    ray_tpu.shutdown()


class ChainEnv(gym.Env if gym else object):
    """Corridor: +1 at the right end, small step cost (the
    test_rllib_learning.py task, plus get_state/set_state so MCTS can use
    the env as its own model)."""

    N = 8
    MAX_STEPS = 24

    def __init__(self, config=None):
        self.observation_space = gym.spaces.Box(0.0, 1.0, (self.N,),
                                                np.float32)
        self.action_space = gym.spaces.Discrete(2)
        self._pos = 0
        self._t = 0

    def _obs(self):
        obs = np.zeros(self.N, np.float32)
        obs[self._pos] = 1.0
        return obs

    def reset(self, *, seed=None, options=None):
        self._pos, self._t = 0, 0
        return self._obs(), {}

    def step(self, action):
        self._t += 1
        self._pos = min(max(self._pos + (1 if action == 1 else -1), 0),
                        self.N - 1)
        done = self._pos == self.N - 1
        trunc = self._t >= self.MAX_STEPS
        reward = 1.0 if done else -0.01
        return self._obs(), reward, done, trunc, {}

    # perfect-information hooks for AlphaZero's search
    def get_state(self):
        return (self._pos, self._t)

    def set_state(self, state):
        self._pos, self._t = state


def _run_until(algo, threshold, max_iters, key="episode_return_mean"):
    best = -np.inf
    for i in range(max_iters):
        result = algo.train()
        value = result.get(key)
        if value is not None and np.isfinite(value):
            best = max(best, value)
        if best >= threshold:
            return best, i + 1
    return best, max_iters


def test_ars_learns_chain(ray4):
    from ray_tpu.rllib import ARSConfig

    cfg = (ARSConfig()
           .environment(ChainEnv)
           .env_runners(num_env_runners=2, num_envs_per_env_runner=2,
                        rollout_fragment_length=48)
           .training(pop_size=8, top_directions=4, noise_stdev=0.5,
                     step_size=0.3))
    algo = cfg.build()
    try:
        best, iters = _run_until(algo, 0.5, 25)
        assert best >= 0.5, f"ARS failed to learn ChainEnv: best={best}"
        # the observation filter really is accumulating state statistics
        assert algo._filter_count > 0
        assert float(algo._filter["var"].max()) != 1.0
    finally:
        algo.stop()


def test_crr_recovers_policy_from_uniform_behavior(ray4, tmp_path):
    """Offline dataset from a UNIFORM behavior policy on a 1-step task
    with reward -(a - tanh(obs0))^2. Plain BC clones uniform noise; CRR's
    advantage weighting must land near the reward-maximizing action."""
    from ray_tpu.rllib import CRRConfig
    from ray_tpu.rllib.offline import JsonWriter

    rng = np.random.default_rng(0)
    n = 4000
    obs = rng.normal(size=(n, 3)).astype(np.float32)
    actions = rng.uniform(-1, 1, size=(n, 1)).astype(np.float32)
    target = np.tanh(obs[:, 0])
    rewards = -np.abs(actions[:, 0] - target).astype(np.float32)
    next_obs = rng.normal(size=(n, 3)).astype(np.float32)
    dones = np.ones(n, np.float32)  # 1-step episodes
    w = JsonWriter(str(tmp_path))
    w.write({"obs": obs, "actions": actions, "rewards": rewards,
             "next_obs": next_obs, "dones": dones})
    w.close()

    cfg = (CRRConfig()
           .training(lr=1e-3, train_batch_size=256,
                     dataset_epochs_per_iter=2, crr_beta=0.25,
                     obs_dim=3, action_dim=1)
           .offline(offline_data=str(tmp_path)))
    algo = cfg.build()
    try:
        for _ in range(6):
            r = algo.step()
        assert np.isfinite(r["critic_loss"])
        assert r["weight_mean"] > 0
        learner = algo.learner_group.local_learner()
        module = learner.module
        test_obs = rng.normal(size=(256, 3)).astype(np.float32)
        _, _, greedy = module.pi(
            learner.params, test_obs,
            __import__("jax").random.key(0))
        err = float(np.mean(np.abs(
            np.asarray(greedy)[:, 0] - np.tanh(test_obs[:, 0]))))
        # uniform behavior has mean abs error ~0.6 against the target
        assert err < 0.25, f"CRR greedy action error {err}"
    finally:
        algo.stop()


def test_alpha_zero_learns_chain(ray4):
    from ray_tpu.rllib import AlphaZeroConfig

    cfg = (AlphaZeroConfig()
           .environment(ChainEnv)
           .env_runners(num_env_runners=2)
           .training(lr=5e-3, train_batch_size=128, num_simulations=24,
                     episodes_per_worker=2, sgd_steps_per_iter=8,
                     temperature_moves=4))
    algo = cfg.build()
    try:
        best, iters = _run_until(algo, 0.8, 12)
        # MCTS lookahead makes the corridor easy: near-optimal fast
        assert best >= 0.8, f"AlphaZero best={best}"
        # and the trained net alone (no search) must act greedily right
        obs = np.zeros(ChainEnv.N, np.float32)
        obs[0] = 1.0
        assert algo.compute_single_action(obs) == 1
    finally:
        algo.stop()


def test_alpha_zero_requires_state_hooks(ray4):
    from ray_tpu.rllib import AlphaZeroConfig

    class NoStateEnv(ChainEnv):
        get_state = None
        set_state = None

    with pytest.raises(ValueError, match="get_state"):
        AlphaZeroConfig().environment(NoStateEnv).build()


def test_dreamer_symlog_twohot_roundtrip():
    """Distributional plumbing invariants: symexp(symlog(x)) == x and
    twohot projection preserves the scalar under the bin expectation."""
    import jax.numpy as jnp

    from ray_tpu.rllib.algorithms.dreamerv3.dreamerv3 import (
        dist_mean, make_bins, symexp, symlog, twohot)

    x = jnp.asarray([-100.0, -1.5, 0.0, 0.3, 7.0, 250.0])
    np.testing.assert_allclose(symexp(symlog(x)), x, rtol=1e-5, atol=1e-5)
    bins = make_bins(41)
    probs = twohot(symlog(x), bins)
    np.testing.assert_allclose(np.asarray(probs.sum(-1)), 1.0, atol=1e-5)
    # expectation under the twohot distribution recovers the input
    recovered = symexp(jnp.sum(probs * bins, -1))
    np.testing.assert_allclose(np.asarray(recovered), np.asarray(x),
                               rtol=1e-2, atol=1e-2)
    # a delta distribution's mean is its bin's value
    delta_logits = jnp.where(jnp.arange(41) == 20, 50.0, -50.0)
    assert abs(float(dist_mean(delta_logits, bins))
               - float(bins[20])) < 1e-4


def test_dreamerv3_learns_chain(ray4):
    from ray_tpu.rllib import DreamerV3Config

    cfg = (DreamerV3Config()
           .environment(ChainEnv)
           .env_runners(num_env_runners=1, num_envs_per_env_runner=4,
                        rollout_fragment_length=16)
           .training(lr=1e-3, deter=64, stoch=4, classes=8,
                     model_hidden=64, imagine_horizon=8,
                     batch_length=16, batch_size_seqs=8,
                     train_ratio=48, entropy_scale=1e-2))
    algo = cfg.build()
    try:
        best, iters = _run_until(algo, 0.5, 40)
        assert best >= 0.5, f"DreamerV3 failed to learn: best={best}"
        r = algo.train()
        assert np.isfinite(r["wm_loss"])
        assert np.isfinite(r["imagined_return_mean"])
    finally:
        algo.stop()


def test_mcts_prefers_rewarding_branch():
    """Search-level unit test: from the second-to-last cell, MCTS visit
    counts must mass on the winning move even with uniform priors."""
    from ray_tpu.rllib.algorithms.alpha_zero import MCTS

    env = ChainEnv()
    env.reset()
    env.set_state((ChainEnv.N - 2, 0))

    def uniform_predict(obs):
        return np.ones(2, np.float32) / 2, 0.0

    mcts = MCTS(env, uniform_predict, num_simulations=64,
                dirichlet_eps=0.0, rng=np.random.default_rng(0))
    obs = np.zeros(ChainEnv.N, np.float32)
    obs[ChainEnv.N - 2] = 1.0
    pi = mcts.search(obs)
    assert pi[1] > 0.7, f"MCTS policy {pi}"
