"""Serve declarative config: schema round-trip, build(), run_config(),
dashboard REST deploy (reference: serve/schema.py + `serve build/deploy`
CLI + dashboard serve REST)."""

import json
import urllib.request

import pytest

import ray_tpu
from ray_tpu import serve


@pytest.fixture(scope="module")
def ray4():
    if not ray_tpu.is_initialized():
        ray_tpu.init(num_cpus=4)
    yield
    try:
        serve.shutdown()
    except Exception:
        pass
    ray_tpu.shutdown()


# module-level so import_path resolution can find it
@serve.deployment(name="Doubler", num_replicas=1)
class Doubler:
    def __call__(self, x):
        return x * 2


doubler_app = Doubler.bind()


def make_app(factor: int = 3):
    @serve.deployment(name="Scaler")
    class Scaler:
        def __call__(self, x):
            return x * factor

    return Scaler.bind()


def test_schema_roundtrip():
    d = serve.build(doubler_app, name="roundtrip", route_prefix="/x",
                    import_path="tests.test_serve_config:doubler_app")
    schema = serve.ServeDeploySchema.from_dict(d)
    assert schema.applications[0].name == "roundtrip"
    assert schema.applications[0].route_prefix == "/x"
    assert schema.applications[0].deployments[0].name == "Doubler"
    assert schema.to_dict() == d


def test_run_config_import_path(ray4):
    config = {
        "applications": [{
            "import_path": "tests.test_serve_config:doubler_app",
            "name": "cfgapp",
            "route_prefix": "/double",
            "deployments": [{"name": "Doubler", "num_replicas": 2}],
        }],
    }
    handles = serve.run_config(config)
    h = handles["cfgapp"]
    assert h.remote(21).result(timeout_s=60) == 42
    st = serve.status("cfgapp")
    assert st["status"] == "RUNNING"
    # the override took: 2 replicas
    assert st["deployments"]["Doubler"]["target_replicas"] == 2
    serve.delete("cfgapp")


def test_run_config_app_builder(ray4):
    """import_path resolving to a builder function taking args."""
    config = {
        "applications": [{
            "import_path": "tests.test_serve_config:make_app",
            "name": "builderapp",
            "route_prefix": "/scale",
            "args": {"factor": 5},
        }],
    }
    handles = serve.run_config(config)
    assert handles["builderapp"].remote(4).result(timeout_s=60) == 20
    serve.delete("builderapp")


def test_grpc_ingress(ray4):
    """Generic gRPC ingress: predict by application metadata, healthz,
    NOT_FOUND for unknown apps (reference: serve gRPC proxy)."""
    import grpc

    serve.shutdown()  # fresh control plane so grpc_options take effect
    serve.start(http_options={"port": 0}, grpc_options={"port": 0})
    serve.run(Doubler.bind(), name="grpcapp", route_prefix="/grpc")
    port = serve.get_grpc_port()
    assert port
    client = serve.ServeGrpcClient(f"127.0.0.1:{port}")
    try:
        assert client.healthz()
        assert client.predict("grpcapp", 21) == 42
        with pytest.raises(grpc.RpcError) as err:
            client.predict("missing-app", 1)
        assert err.value.code() == grpc.StatusCode.NOT_FOUND
    finally:
        client.close()
        serve.delete("grpcapp")


def test_dashboard_serve_rest(ray4):
    from ray_tpu.dashboard import start_dashboard

    port = start_dashboard(port=0)
    config = {
        "applications": [{
            "import_path": "tests.test_serve_config:doubler_app",
            "name": "restapp",
            "route_prefix": "/rest",
        }],
    }
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/api/serve/applications",
        data=json.dumps(config).encode(), method="PUT",
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=60) as resp:
        assert resp.status == 200
    # poll status via GET until RUNNING
    import time
    deadline = time.monotonic() + 60
    apps = {}
    while time.monotonic() < deadline:
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/api/serve/applications",
                timeout=30) as resp:
            apps = json.loads(resp.read())["applications"]
        if apps.get("restapp", {}).get("status") == "RUNNING":
            break
        time.sleep(0.5)
    assert apps["restapp"]["status"] == "RUNNING"
    assert apps["restapp"]["ingress"] == "Doubler"
    # the per-node ingress map rides the same endpoint (reference:
    # serve status proxies section)
    with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/api/serve/applications",
            timeout=30) as resp:
        body = json.loads(resp.read())
    assert any(p.get("healthy") and p.get("http_port")
               for p in body.get("proxies", {}).values()), body
    serve.delete("restapp")
