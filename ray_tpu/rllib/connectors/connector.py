"""Connectors — observation/action transform pipelines between env and
module (reference: rllib/connectors/ — agent connectors transform obs on
the way into inference, action connectors transform the module's output
on the way to env.step; SURVEY §2.4 "connectors (agent/action pipelines,
connectors/ 5.0k)").

Connectors here are stateful per-env transforms running CPU-side in the
env runner's hot loop, so they stay numpy (the jitted module sees the
transformed, fixed-shape batch).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

import numpy as np


class Connector:
    """One transform. ``on_obs`` maps the env observation batch before
    inference (``reset_mask[i]`` flags envs whose obs starts a fresh
    episode — stateful connectors clear that env's history); ``on_action``
    maps the module's action batch before env.step."""

    def on_obs(self, obs: np.ndarray,
               reset_mask: Optional[np.ndarray] = None) -> np.ndarray:
        return obs

    def on_action(self, action: np.ndarray) -> np.ndarray:
        return action

    def on_episode_start(self) -> None:
        pass

    def state(self) -> Dict[str, Any]:
        return {}

    def set_state(self, state: Dict[str, Any]) -> None:
        pass


class ConnectorPipeline(Connector):
    """Obs transforms run in order; action transforms in reverse order
    (reference: connector_pipeline_v2)."""

    def __init__(self, connectors: Optional[List[Connector]] = None):
        self.connectors = list(connectors or [])

    def append(self, connector: Connector) -> "ConnectorPipeline":
        self.connectors.append(connector)
        return self

    def on_obs(self, obs: np.ndarray,
               reset_mask: Optional[np.ndarray] = None) -> np.ndarray:
        for c in self.connectors:
            obs = c.on_obs(obs, reset_mask)
        return obs

    def on_action(self, action: np.ndarray) -> np.ndarray:
        for c in reversed(self.connectors):
            action = c.on_action(action)
        return action

    def on_episode_start(self) -> None:
        for c in self.connectors:
            c.on_episode_start()

    def state(self) -> Dict[str, Any]:
        return {str(i): c.state() for i, c in enumerate(self.connectors)}

    def set_state(self, state: Dict[str, Any]) -> None:
        for i, c in enumerate(self.connectors):
            if str(i) in state:
                c.set_state(state[str(i)])

    @property
    def obs_multiplier(self) -> int:
        """Product of the pipeline's obs-dim multipliers (FrameStack k)."""
        m = 1
        for c in self.connectors:
            m *= getattr(c, "obs_dim_multiplier", 1)
        return m


class NormalizeObs(Connector):
    """Running mean/std normalization (reference: MeanStdFilter agent
    connector). Batched Chan/parallel-Welford merge — O(1) python ops per
    observation batch (this runs in the env runner's hot loop)."""

    def __init__(self, clip: float = 10.0):
        self.clip = clip
        self._count = 0
        self._mean: Optional[np.ndarray] = None
        self._m2: Optional[np.ndarray] = None

    def on_obs(self, obs: np.ndarray,
               reset_mask: Optional[np.ndarray] = None) -> np.ndarray:
        obs = np.asarray(obs, np.float32)
        flat = obs.reshape(-1, obs.shape[-1]).astype(np.float64)
        if self._mean is None:
            self._mean = np.zeros(obs.shape[-1], np.float64)
            self._m2 = np.ones(obs.shape[-1], np.float64)
        n_b = flat.shape[0]
        mean_b = flat.mean(axis=0)
        m2_b = ((flat - mean_b) ** 2).sum(axis=0)
        delta = mean_b - self._mean
        total = self._count + n_b
        self._mean += delta * n_b / total
        self._m2 += m2_b + delta ** 2 * self._count * n_b / total
        self._count = total
        std = np.sqrt(self._m2 / max(self._count, 2)).astype(np.float32)
        out = (obs - self._mean.astype(np.float32)) / np.maximum(std, 1e-6)
        return np.clip(out, -self.clip, self.clip)

    def state(self) -> Dict[str, Any]:
        return {"count": self._count,
                "mean": None if self._mean is None else self._mean.copy(),
                "m2": None if self._m2 is None else self._m2.copy()}

    def set_state(self, state: Dict[str, Any]) -> None:
        self._count = state["count"]
        self._mean = state["mean"]
        self._m2 = state["m2"]


class FrameStack(Connector):
    """Stack the last k observations along the feature axis, with
    PER-ENV history (reference: frame-stacking agent connector). An env's
    rows clear at its episode boundary via ``reset_mask``."""

    def __init__(self, k: int = 4):
        self.k = k
        self.obs_dim_multiplier = k
        self._stack: Optional[np.ndarray] = None  # [E, k, F]

    def on_episode_start(self) -> None:
        self._stack = None

    def on_obs(self, obs: np.ndarray,
               reset_mask: Optional[np.ndarray] = None) -> np.ndarray:
        obs = np.asarray(obs, np.float32)
        batched = obs.ndim == 2
        view = obs if batched else obs[None]
        E, F = view.shape
        if self._stack is None or self._stack.shape[0] != E or \
                self._stack.shape[2] != F:
            self._stack = np.zeros((E, self.k, F), np.float32)
        elif reset_mask is not None and np.any(reset_mask):
            self._stack[np.asarray(reset_mask, bool)] = 0.0
        self._stack = np.roll(self._stack, -1, axis=1)
        self._stack[:, -1] = view
        out = self._stack.reshape(E, self.k * F)
        return out if batched else out[0]


class FlattenObs(Connector):
    """Flatten trailing obs dims to 1-D features (reference: flatten
    agent connector)."""

    def on_obs(self, obs: np.ndarray,
               reset_mask: Optional[np.ndarray] = None) -> np.ndarray:
        obs = np.asarray(obs, np.float32)
        if obs.ndim <= 2:
            return obs
        return obs.reshape(obs.shape[0], -1)


class ActionClip(Connector):
    """Clip continuous actions into the env's box (reference: clip_actions
    action connector)."""

    def __init__(self, low: float = -1.0, high: float = 1.0):
        self.low = low
        self.high = high

    def on_action(self, action: np.ndarray) -> np.ndarray:
        if np.issubdtype(np.asarray(action).dtype, np.floating):
            return np.clip(action, self.low, self.high)
        return action
