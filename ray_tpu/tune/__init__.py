"""ray_tpu.tune — hyperparameter sweep + trial execution engine
(reference: python/ray/tune/ — Tuner :54 in tuner.py, tune.run in tune.py,
TuneController event loop; SURVEY §2.4 Tune row, §7 phase 5).

The controller schedules one lightweight actor per trial; trainer trials
reserve their real (TPU) resources through the trainer's own worker-group
placement group, keeping the sweep engine independent of slice topology.
"""

from __future__ import annotations

from typing import Dict, Optional, Union

from ray_tpu.train._checkpoint import Checkpoint
from ray_tpu.tune.experiment import Trial
from ray_tpu.tune.result_grid import ResultGrid
from ray_tpu.tune import stopper
from ray_tpu.tune.logger import (
    Callback, CSVLoggerCallback, JsonLoggerCallback, LoggerCallback,
    TBXLoggerCallback)
from ray_tpu.tune.schedulers import (
    ASHAScheduler, AsyncHyperBandScheduler, FIFOScheduler,
    HyperBandScheduler, MedianStoppingRule, PB2, PopulationBasedTraining,
    TrialScheduler)
from ray_tpu.tune.stopper import (
    CombinedStopper, ExperimentPlateauStopper, FunctionStopper,
    MaximumIterationStopper, Stopper, TimeoutStopper, TrialPlateauStopper)
from ray_tpu.tune.search.basic_variant import BasicVariantGenerator
from ray_tpu.tune.search.sample import (
    choice, grid_search, lograndint, loguniform, quniform, randint,
    sample_from, uniform)
from ray_tpu.tune.search.searcher import ConcurrencyLimiter, Searcher
from ray_tpu.tune.trainable import (
    FunctionTrainable, Trainable, with_parameters, wrap_function)
from ray_tpu.tune.tuner import TuneConfig, Tuner

__all__ = [
    "Tuner", "TuneConfig", "Trainable", "FunctionTrainable", "Trial",
    "ResultGrid", "report", "get_checkpoint", "with_parameters",
    "uniform", "quniform", "loguniform", "randint", "lograndint", "choice",
    "sample_from", "grid_search", "Searcher", "ConcurrencyLimiter",
    "BasicVariantGenerator", "TrialScheduler", "FIFOScheduler",
    "ASHAScheduler", "AsyncHyperBandScheduler", "HyperBandScheduler",
    "MedianStoppingRule", "PB2",
    "PopulationBasedTraining", "run", "stopper", "Stopper",
    "CombinedStopper", "ExperimentPlateauStopper", "FunctionStopper",
    "MaximumIterationStopper", "TimeoutStopper", "TrialPlateauStopper",
    "Callback", "LoggerCallback", "CSVLoggerCallback",
    "JsonLoggerCallback", "TBXLoggerCallback",
]


def report(metrics: Dict, checkpoint: Optional[Checkpoint] = None) -> None:
    """Report one iteration's metrics (+ optional checkpoint) from inside a
    function trainable (reference: ray.tune.report / train.report)."""
    from ray_tpu.tune.trainable import _get_fn_session

    _get_fn_session().report(metrics, checkpoint)


def get_checkpoint() -> Optional[Checkpoint]:
    """The checkpoint to resume from, inside a function trainable."""
    from ray_tpu.tune.trainable import _get_fn_session

    return _get_fn_session().loaded_checkpoint


def run(trainable, *, config: Optional[Dict] = None, metric=None,
        mode="max", num_samples: int = 1, search_alg=None, scheduler=None,
        stop=None, storage_path=None, name=None,
        resources_per_trial=None, **_ignored) -> ResultGrid:
    """Legacy ``tune.run`` shim over Tuner (reference: tune/tune.py:276)."""
    from ray_tpu.air.config import RunConfig

    tuner = Tuner(
        trainable,
        param_space=config,
        tune_config=TuneConfig(metric=metric, mode=mode,
                               num_samples=num_samples,
                               search_alg=search_alg, scheduler=scheduler),
        run_config=RunConfig(name=name, storage_path=storage_path,
                             stop=stop),
        resources_per_trial=resources_per_trial,
    )
    return tuner.fit()
