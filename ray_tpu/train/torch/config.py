"""Torch backend: rendezvous + process group init (reference:
python/ray/train/torch/config.py:129 _TorchBackend — rank-0 address
broadcast then ``dist.init_process_group`` :91).

This image ships CPU torch, so gloo is the default (and only sensible)
backend; the TPU-native story remains JaxTrainer — TorchTrainer exists so
torch training code ports over unchanged.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

from ray_tpu.train._internal.backend_executor import Backend
from ray_tpu.train._internal.worker_group import WorkerGroup


@dataclasses.dataclass
class TorchConfig:
    backend: str = "gloo"
    timeout_s: int = 1800

    @property
    def backend_cls(self):
        return TorchBackend


def _setup_torch_process_group(rank: int, world_size: int, master_addr: str,
                               master_port: int, backend: str,
                               timeout_s: int) -> None:
    import datetime
    import os

    import torch.distributed as dist

    os.environ["MASTER_ADDR"] = master_addr
    os.environ["MASTER_PORT"] = str(master_port)
    os.environ["RANK"] = str(rank)
    os.environ["WORLD_SIZE"] = str(world_size)
    if not dist.is_initialized():
        dist.init_process_group(
            backend=backend, rank=rank, world_size=world_size,
            timeout=datetime.timedelta(seconds=timeout_s))


class TorchBackend(Backend):
    def on_start(self, worker_group: WorkerGroup,
                 backend_config: TorchConfig) -> None:
        """Same bounded-retry rendezvous contract as JaxBackend: a fresh
        master port per attempt (free-port race), decorrelated-jitter
        pacing, typed exhaustion."""
        import time as _time

        import ray_tpu
        from ray_tpu._private.async_util import DecorrelatedJitterBackoff
        from ray_tpu._private.config import CONFIG
        from ray_tpu.exceptions import TrainRendezvousError
        from ray_tpu.train._internal.util import find_free_port

        metas = worker_group.node_metas()
        master_addr = metas[0]["hostname"]
        attempts = max(1, int(CONFIG.train_rendezvous_max_retries))
        backoff = DecorrelatedJitterBackoff(base_s=0.2, cap_s=2.0)
        last: Optional[BaseException] = None
        master_port = 0
        for attempt in range(1, attempts + 1):
            master_port = worker_group.execute_single(0, find_free_port)
            try:
                ray_tpu.get([
                    w.execute.remote(_setup_torch_process_group, i,
                                     len(worker_group), master_addr,
                                     master_port, backend_config.backend,
                                     backend_config.timeout_s)
                    for i, w in enumerate(worker_group.workers)
                ], timeout=float(CONFIG.train_rendezvous_timeout_s) + 30.0)
                return
            except Exception as e:
                last = e
            if attempt < attempts:
                _time.sleep(backoff.next_delay())
        raise TrainRendezvousError(
            coordinator=f"{master_addr}:{master_port}", attempts=attempts,
            reason=str(last)[:300] if last else "unknown") from last

    def on_shutdown(self, worker_group: WorkerGroup,
                    backend_config: TorchConfig) -> None:
        def teardown():
            try:
                import torch.distributed as dist

                if dist.is_initialized():
                    dist.destroy_process_group()
            except Exception:
                pass

        import ray_tpu as _ray

        try:
            # bounded for the same reason as the jax backend: a worker
            # wedged on a dead peer's collective must not stall teardown
            _ray.get([w.execute.remote(teardown)
                      for w in worker_group.workers], timeout=10.0)
        except Exception:
            pass
