"""Collective types (reference: python/ray/util/collective/types.py).

The reference enumerates NCCL/GLOO backends; the TPU-native build replaces
them with:

- ``Backend.XLA`` — device-mesh collectives: intra-member reduction over the
  member's local ``jax.Device`` mesh (ICI), cross-member combine over the
  control plane (DCN). On a real multi-host pod the group *is* a global mesh
  (``jax.distributed``) and every op lowers to one ``jax.lax`` collective.
- ``Backend.CPU`` — gloo-equivalent host-memory backend for CPU tensors,
  rendezvous + transport via a named store actor.
"""

from __future__ import annotations

import dataclasses
from enum import Enum


class Backend(str, Enum):
    XLA = "xla"
    CPU = "cpu"
    # Aliases accepted for reference-API compatibility: "nccl"/"gloo" map to
    # the closest TPU-native backend rather than erroring out.
    @classmethod
    def coerce(cls, name: "str | Backend") -> "Backend":
        if isinstance(name, Backend):
            return name
        name = str(name).lower()
        if name in ("xla", "tpu", "nccl"):
            return cls.XLA
        if name in ("cpu", "gloo", "host"):
            return cls.CPU
        raise ValueError(f"Unknown collective backend: {name!r}")


class ReduceOp(str, Enum):
    SUM = "sum"
    PRODUCT = "product"
    MIN = "min"
    MAX = "max"


@dataclasses.dataclass
class AllReduceOptions:
    reduceOp: ReduceOp = ReduceOp.SUM
    timeout_ms: int = 30000


@dataclasses.dataclass
class BarrierOptions:
    timeout_ms: int = 30000


@dataclasses.dataclass
class ReduceOptions:
    reduceOp: ReduceOp = ReduceOp.SUM
    root_rank: int = 0
    timeout_ms: int = 30000


@dataclasses.dataclass
class BroadcastOptions:
    root_rank: int = 0
    timeout_ms: int = 30000


@dataclasses.dataclass
class AllGatherOptions:
    timeout_ms: int = 30000


@dataclasses.dataclass
class ReduceScatterOptions:
    reduceOp: ReduceOp = ReduceOp.SUM
    timeout_ms: int = 30000


@dataclasses.dataclass
class SendOptions:
    dst_rank: int = 0
    timeout_ms: int = 30000


@dataclasses.dataclass
class RecvOptions:
    src_rank: int = 0
    timeout_ms: int = 30000
