"""ray_tpu.serve — online model serving (reference: python/ray/serve/ —
serve.run api.py:439, @serve.deployment :246, controller/proxy/replica
triad; SURVEY §3.5 call stack, §7 phase 6).

TPU-first deviations: dynamic batching speaks ``allowed_batch_sizes`` so
dispatch aligns with compiled XLA shapes; multiplexing targets LoRA-adapter
serving on a shared base model.
"""

from __future__ import annotations

import time
from typing import Any, Dict, Optional

import ray_tpu
from ray_tpu.exceptions import BackPressureError
from ray_tpu.serve._private.engine import ContinuousBatchingEngine
from ray_tpu.serve.batching import batch, pad_batch
from ray_tpu.serve.deployment import (
    Application, AutoscalingConfig, Deployment, deployment)
from ray_tpu.serve.handle import DeploymentHandle, DeploymentResponse
from ray_tpu.serve.multiplex import get_multiplexed_model_id, multiplexed
from ray_tpu.serve._private.controller import (
    CONTROLLER_NAME, SERVE_NAMESPACE, ServeController)
from ray_tpu.serve._private.proxy import ProxyActor, Request
from ray_tpu.serve._private.replica import _HandlePlaceholder
from ray_tpu.serve.asgi import Response, StreamingResponse, ingress
from ray_tpu.serve.drivers import (
    DAGDriver, InputNode, json_request, starlette_request)
from ray_tpu.serve.grpc_util import ServeGrpcClient
from ray_tpu.serve.schema import (
    DeploymentSchema, HTTPOptionsSchema, ServeApplicationSchema,
    ServeDeploySchema, build_app_schema)

__all__ = [
    "deployment", "Deployment", "Application", "AutoscalingConfig",
    "DeploymentHandle", "DeploymentResponse", "Request",
    "start", "run", "shutdown", "delete", "status", "get_app_handle",
    "get_deployment_handle", "batch", "pad_batch", "multiplexed",
    "BackPressureError", "ContinuousBatchingEngine",
    "get_multiplexed_model_id", "build", "run_config",
    "DeploymentSchema", "ServeApplicationSchema", "ServeDeploySchema",
    "HTTPOptionsSchema", "ServeGrpcClient", "get_grpc_port",
    "get_proxy_info",
    "ingress", "Response", "StreamingResponse",
    "DAGDriver", "InputNode", "json_request", "starlette_request",
]

PROXY_NAME = "SERVE_PROXY"
_http_port: Optional[int] = None
_grpc_port: Optional[int] = None


def start(http_options: Optional[Dict] = None, detached: bool = True,
          grpc_options: Optional[Dict] = None):
    """Start the Serve control plane: controller + one HTTP (+ gRPC)
    proxy PER NODE, controller-managed (reference: serve.start /
    _private/api.py; per-node proxies proxy.py:1097 + proxy_state.py;
    gRPC ingress via grpc_options={"port": ...})."""
    global _http_port, _grpc_port
    http_options = http_options or {}
    try:
        ctrl = ray_tpu.get_actor(CONTROLLER_NAME, namespace=SERVE_NAMESPACE)
        if (grpc_options or {}).get("port") is not None:
            # only reject when the controller DEFINITIVELY reports no gRPC
            # ingress — a failed/slow query must not produce a false
            # "running without gRPC" error
            try:
                info = ray_tpu.get(ctrl.get_proxy_info.remote(), timeout=10)
                has_grpc = any(p.get("grpc_port") is not None
                               for p in info.values()) if info else True
            except Exception:
                has_grpc = True  # unknown: assume configured
            if not has_grpc:
                raise RuntimeError(
                    "serve is already running without a gRPC ingress; call "
                    "serve.shutdown() first to start with grpc_options")
        return
    except RuntimeError:
        raise
    except Exception:
        pass
    port = http_options.get("port", 8000)
    host = http_options.get("host", "127.0.0.1")
    grpc_port = (grpc_options or {}).get("port")
    ctrl = ray_tpu.remote(ServeController).options(
        name=CONTROLLER_NAME, namespace=SERVE_NAMESPACE,
        max_concurrency=64, num_cpus=0.1).remote(http_port=port)
    ray_tpu.get(
        ctrl.start_proxies.remote(port=port, host=host, grpc_port=grpc_port),
        timeout=120)
    info = _local_proxy_info(ctrl, timeout=60)
    if info is None:
        # fail fast: a control plane without a single healthy ingress is
        # not "started" — silently continuing surfaces later as opaque
        # connection refusals on the first request. Tear the just-created
        # controller down too, or a retrying start() would hit the
        # "already running" early-return above and report success with
        # zero proxies.
        try:
            ray_tpu.kill(ctrl)
        except Exception:
            pass
        raise RuntimeError(
            "serve.start(): no healthy proxy became available within the "
            "deadline; check the controller/proxy actor logs in the "
            "session's logs/ directory")
    _http_port = info.get("http_port")
    _grpc_port = info.get("grpc_port")


def _local_proxy_info(ctrl=None, timeout: float = 30.0) -> Optional[Dict]:
    """The proxy record for THIS driver's node (falling back to any
    healthy proxy): requests should enter through the node-local ingress
    (reference: proxy_router picking the local proxy)."""
    ctrl = ctrl or _controller()
    my_node = None
    try:
        my_node = ray_tpu.get_runtime_context().get_node_id()
    except Exception:
        pass
    deadline = time.monotonic() + timeout
    while True:
        info = ray_tpu.get(ctrl.get_proxy_info.remote(), timeout=30)
        healthy = {nid: p for nid, p in info.items() if p.get("healthy")}
        if healthy:
            if my_node in healthy:
                return healthy[my_node]
            if my_node is None or time.monotonic() > deadline - timeout / 2:
                # node id unknown, or the local proxy is slow to come up.
                # Another node's proxy is only reachable through its
                # advertised host — a loopback bind on a DIFFERENT host is
                # useless, but on single-host (test) clusters every
                # "node" shares this machine, so loopback still works.
                return next(iter(healthy.values()))
        if time.monotonic() > deadline:
            return None
        time.sleep(0.2)


_PORT_UNQUERIED = object()  # distinct from "queried, ingress absent"


def get_http_port() -> Optional[int]:
    """The node-local proxy's bound port (0 in http_options picks a free
    one). Queried from the controller when this process didn't start
    Serve itself (a second driver connecting to a running cluster)."""
    global _http_port
    if _http_port is None:
        _http_port = _proxy_port("http_port", default=None)
    return _http_port


def get_grpc_port() -> Optional[int]:
    global _grpc_port
    if _grpc_port is None:
        _grpc_port = _proxy_port("grpc_port", default=None)
    return _grpc_port


def get_proxy_info() -> Dict[str, Dict]:
    """{node_id: {name, http_port, grpc_port, healthy}} for every node's
    ingress proxy (reference: serve status proxies section)."""
    try:
        return ray_tpu.get(_controller().get_proxy_info.remote(), timeout=30)
    except Exception:
        return {}


_port_cache: dict = {}


def _proxy_port(field: str, default=None):
    # cache definitive answers (including "no such ingress") so pollers
    # don't pay an actor round trip per call; failures are NOT cached
    if field in _port_cache:
        return _port_cache[field]
    try:
        info = _local_proxy_info(timeout=10)
        if info is None:
            return default
        value = info.get(field)
    except Exception:
        return default
    _port_cache[field] = value
    return value


def _controller():
    return ray_tpu.get_actor(CONTROLLER_NAME, namespace=SERVE_NAMESPACE)


def _transform_graph(value, fn):
    from ray_tpu.serve.deployment import map_graph_values

    def leaf(a):
        if isinstance(a, (Application, _HandlePlaceholder)):
            return fn(a)
        return a

    return map_graph_values(value, leaf)


def _build_specs(app: Application):
    """Flatten the bind graph into wire specs; nested Applications become
    handle placeholders (reference: deployment_graph_build.py)."""
    import cloudpickle

    nodes = app.walk()
    specs = []
    for node in nodes:
        d = node.deployment

        def to_placeholder(a):
            if isinstance(a, Application):
                return _HandlePlaceholder("__APP__", a.deployment.name)
            return a

        args = tuple(_transform_graph(a, to_placeholder)
                     for a in node.args)
        kwargs = {k: _transform_graph(v, to_placeholder)
                  for k, v in node.kwargs.items()}
        auto = d.autoscaling_config
        specs.append({
            "name": d.name,
            "blob": cloudpickle.dumps(d.func_or_class),
            "init_blob": cloudpickle.dumps((args, kwargs)),
            "num_replicas": d.num_replicas,
            "max_ongoing_requests": d.max_ongoing_requests,
            "max_queued_requests": d.max_queued_requests,
            "user_config": d.user_config,
            "autoscaling_config": auto.__dict__ if auto else None,
            "ray_actor_options": d.ray_actor_options,
            "health_check_period_s": d.health_check_period_s,
            "graceful_shutdown_timeout_s": d.graceful_shutdown_timeout_s,
        })
    return specs


def run(target: Application, *, name: str = "default",
        route_prefix: str = "/", _blocking: bool = True,
        wait_timeout_s: float = 120.0) -> DeploymentHandle:
    """Deploy an application and return a handle to its ingress
    (reference: serve.run api.py:439)."""
    start()
    specs = _build_specs(target)
    # resolve the placeholder app name now that we know it
    import cloudpickle

    for spec in specs:
        args, kwargs = cloudpickle.loads(spec["init_blob"])

        def fix(a):
            if isinstance(a, _HandlePlaceholder):
                a.app_name = name
            return a

        args = tuple(_transform_graph(a, fix) for a in args)
        kwargs = {k: _transform_graph(v, fix) for k, v in kwargs.items()}
        spec["init_blob"] = cloudpickle.dumps((args, kwargs))
    ingress = target.deployment.name
    ctrl = _controller()
    ray_tpu.get(
        ctrl.deploy_application.remote(name, specs, ingress, route_prefix),
        timeout=60)
    if _blocking:
        deadline = time.monotonic() + wait_timeout_s
        st: Dict = {}
        while True:
            st = ray_tpu.get(ctrl.get_app_status.remote(name), timeout=30)
            if st["status"] == "RUNNING":
                break
            if time.monotonic() > deadline:
                raise TimeoutError(
                    f"application {name!r} not RUNNING within "
                    f"{wait_timeout_s}s: {st}")
            time.sleep(0.1)
    return DeploymentHandle(name, ingress)


def build(target: Application, *, name: str = "default",
          route_prefix: str = "/",
          import_path: str = "") -> Dict:
    """Snapshot an Application into the declarative config dict that
    ``run_config`` / ``PUT /api/serve/applications`` consume (reference:
    `serve build` CLI emitting ServeDeploySchema YAML)."""
    app_schema = build_app_schema(target, name=name,
                                  route_prefix=route_prefix,
                                  import_path=import_path)
    return ServeDeploySchema(applications=[app_schema]).to_dict()


def run_config(config, *, _blocking: bool = True) -> Dict[str, Any]:
    """Deploy every application in a ServeDeploySchema-shaped dict
    (reference: `serve deploy` → controller deploy_config path). Returns
    {app_name: ingress handle}."""
    schema = (config if isinstance(config, ServeDeploySchema)
              else ServeDeploySchema.from_dict(config))
    start(http_options=schema.http_options.to_dict())
    handles: Dict[str, Any] = {}
    for app_schema in schema.applications:
        app = app_schema.resolve()
        handles[app_schema.name] = run(
            app, name=app_schema.name,
            route_prefix=app_schema.route_prefix, _blocking=_blocking)
    return handles


def status(name: str = "default") -> Dict:
    try:
        return ray_tpu.get(
            _controller().get_app_status.remote(name), timeout=30)
    except Exception:
        return {"status": "NOT_STARTED", "deployments": {}}


def delete(name: str, _blocking: bool = True) -> None:
    ray_tpu.get(_controller().delete_application.remote(name), timeout=60)


def get_app_handle(name: str = "default") -> DeploymentHandle:
    routes = ray_tpu.get(_controller().get_routes.remote(), timeout=30)
    for prefix, (app, ingress) in routes.items():
        if app == name:
            return DeploymentHandle(name, ingress)
    raise ValueError(f"no application named {name!r}")


def get_deployment_handle(deployment_name: str,
                          app_name: str = "default") -> DeploymentHandle:
    return DeploymentHandle(app_name, deployment_name)


def shutdown() -> None:
    """Tear down all applications + the control plane."""
    global _http_port, _grpc_port
    _grpc_port = None
    _port_cache.clear()
    try:
        ctrl = _controller()
    except Exception:
        return
    # controller.shutdown kills the per-node proxies; sweep by name as a
    # backup in case the controller wedged mid-shutdown
    try:
        proxy_names = [p["name"] for p in
                       ray_tpu.get(ctrl.get_proxy_info.remote(),
                                   timeout=10).values()]
    except Exception:
        proxy_names = []
    try:
        ray_tpu.get(ctrl.shutdown.remote(), timeout=60)
    except Exception:
        pass
    for actor_name in (*proxy_names, PROXY_NAME, CONTROLLER_NAME):
        try:
            ray_tpu.kill(
                ray_tpu.get_actor(actor_name, namespace=SERVE_NAMESPACE))
        except Exception:
            pass
    _http_port = None
