"""Shared-memory object store (plasma analog).

Parity with the reference's plasma store (reference:
``src/ray/object_manager/plasma/store.h:55``, ``client.cc``): node-local
shared memory holding immutable sealed objects, zero-copy reads from every
process on the node, LRU eviction of unpinned objects, and disk spilling under
pressure (reference: ``src/ray/raylet/local_object_manager.h:110``).

Design deviation (deliberate, simpler + TPU-friendly): instead of one big
dlmalloc'd shm segment with fd passing (reference: ``plasma/dlmalloc.cc``,
``fling.cc``), every object is its own tmpfs-backed file under
``/dev/shm/<session>/<node>/``. Creation writes a ``.tmp`` file and *seal* is
an atomic rename, so a reader can mmap any visible file with no further
handshake — the store server is only consulted for accounting, waiting and
eviction, never on the read path. mmap'd views feed ``jax.device_put``
directly, so shm → HBM needs no intermediate host copy.
"""

from __future__ import annotations

import mmap
import os
import threading
import time
from collections import OrderedDict
from typing import Dict, List, Optional, Tuple

from ray_tpu._private.config import CONFIG
from ray_tpu._private.ids import ObjectID
from ray_tpu.exceptions import ObjectStoreFullError


def default_store_capacity() -> int:
    cap = CONFIG.object_store_memory_bytes
    if cap:
        return cap
    try:
        import psutil

        return int(psutil.virtual_memory().total * 0.3)
    except Exception:
        return 2 << 30


class StoreClient:
    """Direct filesystem access to a node's object directory.

    Used by every process on the node (driver, workers, agent). The agent owns
    the authoritative accounting (`StoreDirectory` below); clients create and
    read objects directly through tmpfs and only *notify* the agent.
    """

    def __init__(self, store_dir: str):
        self.store_dir = store_dir
        os.makedirs(store_dir, exist_ok=True)

    # -- write path ----------------------------------------------------------
    def create(self, object_id: ObjectID, size: int) -> Tuple[memoryview, object]:
        """Allocate an unsealed object; returns (writable view, handle)."""
        tmp = os.path.join(self.store_dir, f".tmp-{object_id.hex()}")
        fd = os.open(tmp, os.O_CREAT | os.O_RDWR | os.O_TRUNC, 0o600)
        try:
            os.ftruncate(fd, max(size, 1))
            mm = mmap.mmap(fd, max(size, 1))
        finally:
            os.close(fd)
        return memoryview(mm), (tmp, mm)

    def seal(self, object_id: ObjectID, handle: object) -> None:
        tmp, mm = handle
        mm.flush()
        final = os.path.join(self.store_dir, object_id.hex())
        os.rename(tmp, final)

    def abort(self, handle: object) -> None:
        tmp, mm = handle
        try:
            mm.close()
        except Exception:
            pass
        try:
            os.unlink(tmp)
        except OSError:
            pass

    def put_bytes(self, object_id: ObjectID, data: bytes) -> int:
        view, handle = self.create(object_id, len(data))
        view[: len(data)] = data
        self.seal(object_id, handle)
        return len(data)

    # -- read path -----------------------------------------------------------
    def contains(self, object_id: ObjectID) -> bool:
        return os.path.exists(os.path.join(self.store_dir, object_id.hex()))

    def get_view(self, object_id: ObjectID) -> Optional[memoryview]:
        """Zero-copy read of a sealed object. Returns None if absent.

        The returned memoryview aliases an mmap that stays alive as long as
        the view is referenced (mmap close is deferred to GC).
        """
        path = os.path.join(self.store_dir, object_id.hex())
        try:
            fd = os.open(path, os.O_RDONLY)
        except FileNotFoundError:
            return None
        try:
            size = os.fstat(fd).st_size
            if size == 0:
                return memoryview(b"")
            mm = mmap.mmap(fd, size, prot=mmap.PROT_READ)
        finally:
            os.close(fd)
        return memoryview(mm)

    def delete(self, object_id: ObjectID) -> int:
        path = os.path.join(self.store_dir, object_id.hex())
        try:
            size = os.path.getsize(path)
            os.unlink(path)
            return size
        except OSError:
            return 0


class NativeStoreClient:
    """StoreClient-compatible facade over the C++ shm arena store
    (ray_tpu/_native/store.cc — the plasma analog; reference:
    ``src/ray/object_manager/plasma/client.cc``).

    All objects live in ONE mmap'd segment shared by every process on the
    node; create/seal/lookup are lock-protected table operations in shared
    memory, no per-op IPC. Reads are pinned in the C++ store for exactly as
    long as any Python alias of the buffer is alive (a ``weakref.finalize``
    on the ctypes slice releases the pin), so LRU eviction can never pull
    memory out from under a deserialized numpy/jax array.

    Enabled with ``RAY_TPU_STORE_BACKEND=native``.
    """

    def __init__(self, store_dir: str, capacity: Optional[int] = None):
        from ray_tpu import _native

        self.store_dir = store_dir
        os.makedirs(store_dir, exist_ok=True)
        seg = os.path.join(store_dir, "segment")
        # First process on the node creates the segment (O_EXCL in C++);
        # losers of the race attach.
        self._store = _native.NativeStore(
            seg, capacity=capacity or default_store_capacity(), create=True)

    # -- write path ----------------------------------------------------------
    def create(self, object_id: ObjectID, size: int) -> Tuple[memoryview, object]:
        view = self._store.create(object_id.binary(), size)
        if view is None:
            raise ObjectStoreFullError(
                f"native store cannot allocate {size} bytes")
        return view, object_id.binary()

    def seal(self, object_id: ObjectID, handle: object) -> None:
        self._store.seal(handle)

    def abort(self, handle: object) -> None:
        self._store.abort(handle)

    def put_bytes(self, object_id: ObjectID, data: bytes) -> int:
        view, handle = self.create(object_id, len(data))
        view[: len(data)] = data
        self.seal(object_id, handle)
        return len(data)

    # -- read path -----------------------------------------------------------
    def contains(self, object_id: ObjectID) -> bool:
        return self._store.contains(object_id.binary())

    def get_view(self, object_id: ObjectID) -> Optional[memoryview]:
        return self._store.get_pinned_view(object_id.binary())

    def pin(self, object_id: ObjectID) -> Optional[memoryview]:
        """Pin without the auto-release finalizer (caller must release)."""
        return self._store.get(object_id.binary())

    def release(self, object_id: ObjectID) -> None:
        self._store.release(object_id.binary())

    def delete(self, object_id: ObjectID) -> int:
        # Pinned objects refuse deletion in C++ (rc=-2); they are reclaimed
        # by LRU eviction once the last reader releases.
        return 1 if self._store.delete(object_id.binary()) else 0

    def stats(self) -> Dict:
        return self._store.stats()


def make_store_client(store_dir: str, capacity: Optional[int] = None):
    """Backend factory: the C++ arena store (default — ~4.6x the large-put
    bandwidth of the tmpfs backend on one core) with tmpfs file-per-object
    as explicit opt-out (``RAY_TPU_STORE_BACKEND=tmpfs``) and automatic
    fallback when the native toolchain is unavailable."""
    backend = os.environ.get("RAY_TPU_STORE_BACKEND", "native")
    if backend == "native":
        try:
            return NativeStoreClient(store_dir, capacity)
        except Exception as e:
            # A node-wide backend mismatch makes objects invisible across
            # processes, so the fallback must be loud.
            import logging

            logging.getLogger("ray_tpu").error(
                "RAY_TPU_STORE_BACKEND=native but the native store failed "
                "(%s); THIS PROCESS falls back to the tmpfs backend — other "
                "processes on the node may not see its objects", e)
    return StoreClient(store_dir)


class StoreDirectory:
    """Authoritative per-node accounting: sizes, pins, LRU, tiered spill.

    Runs inside the node agent (the raylet analog). Thread-safe; called from
    the agent event loop and RPC handlers.

    Spill is tiered (device object plane, ISSUE 9): shm → disk →
    remote-holder. The disk tier is the classic spill file; the remote
    tier drops the local copy entirely against a RECORDED remote holder
    (``note_remote_source``) — restoring it is a plain pull-plane fetch,
    so broadcast-tree reads can source an object from whichever tier a
    holder currently keeps it in. Demotion to the remote tier happens
    when the disk tier is unavailable (write failure) or over its
    ``object_spill_disk_max_bytes`` budget, and only ever for objects
    with a known live source elsewhere.
    """

    def __init__(self, store_dir: str, capacity: Optional[int] = None,
                 spill_dir: Optional[str] = None):
        self.client = make_store_client(store_dir, capacity)
        # Native backend: the C++ arena enforces capacity and runs LRU
        # eviction itself (store.cc evict_for), so this directory only keeps
        # pins and spill state.
        self.native = isinstance(self.client, NativeStoreClient)
        self.capacity = capacity or default_store_capacity()
        self.used = 0
        self.spill_dir = spill_dir or os.path.join(store_dir, "spill")
        self._lock = threading.RLock()
        # object hex -> size, insertion-ordered for LRU (move_to_end on touch)
        self._objects: "OrderedDict[str, int]" = OrderedDict()
        self._pins: Dict[str, int] = {}
        self._native_pins: Dict[str, Optional[memoryview]] = {}
        self._spilled: "OrderedDict[str, int]" = OrderedDict()  # disk tier
        self._remote: "OrderedDict[str, int]" = OrderedDict()   # remote tier
        # native-arena deletes refused because a reader still pinned the
        # object (C++ rc=-2): retried on later bookkeeping ops so the
        # bytes free when the last view drops instead of lingering until
        # LRU pressure — exact accounting for the memory debugger
        self._deferred_deletes: set = set()
        # hex -> [addr]: holders known to keep a sealed copy (recorded by
        # the pull plane; survives local eviction so the remote tier can
        # point a restore pull at them)
        self._remote_sources: Dict[str, List[Dict]] = {}
        # hex -> {"replayable": bool, "exec_ms": float EMA} lineage hints
        # from ObjectSealed (ISSUE 17): replayable copies are preferred
        # eviction victims (cheapest replay first), and — as the final
        # tier — droppable with NO remote holder, because their owner
        # rebuilds them by task replay on the next failed pull
        self._lineage_info: Dict[str, Dict] = {}
        self.num_evictions = 0
        self.num_spills = 0
        self.num_restores = 0
        self.num_remote_demotions = 0
        self.num_lineage_evictions = 0

    # -- bookkeeping ---------------------------------------------------------
    def _retry_deferred_deletes(self) -> None:
        """Native deletes refused while a reader pinned the object; the
        pin is gone once the Python view dies, so retry cheaply from the
        bookkeeping paths (no-op when the set is empty)."""
        if not self._deferred_deletes:
            return
        with self._lock:
            for hex_id in list(self._deferred_deletes):
                oid = ObjectID.from_hex(hex_id)
                # settle on EITHER outcome: deleted now, or already gone
                # (arena LRU beat us) — a not-found id must not park here
                # forever re-paying a futile C call per bookkeeping op
                if self.client.delete(oid) or not self.client.contains(oid):
                    self._deferred_deletes.discard(hex_id)

    def on_sealed(self, object_id_hex: str, size: int) -> None:
        self._retry_deferred_deletes()
        with self._lock:
            # a re-seal (lineage recovery re-announce) revives the
            # object: a deferred delete from its past life must not
            # reap the new copy
            self._deferred_deletes.discard(object_id_hex)
            self._remote.pop(object_id_hex, None)  # restored locally
            if object_id_hex in self._objects:
                return
            if not self.native:
                self._ensure_space(size)
            self._objects[object_id_hex] = size
            self.used += size

    def note_lineage(self, object_id_hex: str, replayable: bool,
                     exec_ms: float) -> None:
        """Record the seal's lineage hints (ISSUE 17). Exec time is kept
        as an EMA across re-seals (same 0.8/0.2 curve as the lease pools'
        exec model) so a flaky first run doesn't mislabel a copy cheap."""
        with self._lock:
            info = self._lineage_info.get(object_id_hex)
            if info is None:
                self._lineage_info[object_id_hex] = {
                    "replayable": bool(replayable),
                    "exec_ms": float(exec_ms),
                }
            else:
                info["replayable"] = bool(replayable)
                info["exec_ms"] = 0.8 * info["exec_ms"] + 0.2 * float(exec_ms)

    def lineage_replayable(self, object_id_hex: str) -> bool:
        with self._lock:
            info = self._lineage_info.get(object_id_hex)
            return bool(info and info.get("replayable"))

    def note_remote_source(self, object_id_hex: str,
                           addrs: List[Dict]) -> None:
        """Record holders known to keep a sealed copy (the nodes a pull
        fetched from). These make the object eligible for remote-tier
        demotion and seed the restore pull's holder list."""
        if not addrs:
            return
        with self._lock:
            known = self._remote_sources.setdefault(object_id_hex, [])
            for addr in addrs:
                entry = {"host": addr.get("host"), "port": addr.get("port")}
                if entry not in known:
                    known.append(entry)

    def remote_sources_for(self, object_id_hex: str) -> List[Dict]:
        with self._lock:
            return list(self._remote_sources.get(object_id_hex, []))

    def forget_remote_source(self, addr: Dict) -> None:
        """A holder died: stop offering it as a restore source."""
        entry = {"host": addr.get("host"), "port": addr.get("port")}
        with self._lock:
            for hex_id in list(self._remote_sources):
                lst = self._remote_sources[hex_id]
                if entry in lst:
                    lst.remove(entry)
                    if not lst:
                        self._remote_sources.pop(hex_id)

    def touch(self, object_id_hex: str) -> None:
        with self._lock:
            if object_id_hex in self._objects:
                self._objects.move_to_end(object_id_hex)

    def pin(self, object_id_hex: str) -> None:
        with self._lock:
            n = self._pins.get(object_id_hex, 0)
            if n == 0 and self.native:
                # forward the pin into the C++ arena so its LRU eviction
                # cannot reclaim a primary copy out from under us
                self._native_pins[object_id_hex] = self.client.pin(
                    ObjectID.from_hex(object_id_hex))
            self._pins[object_id_hex] = n + 1

    def unpin(self, object_id_hex: str) -> None:
        with self._lock:
            n = self._pins.get(object_id_hex, 0) - 1
            if n <= 0:
                self._pins.pop(object_id_hex, None)
                if self.native and self._native_pins.pop(
                        object_id_hex, None) is not None:
                    self.client.release(ObjectID.from_hex(object_id_hex))
            else:
                self._pins[object_id_hex] = n

    def list_entries(self, limit: int = 1000) -> list:
        """Snapshot of resident + spilled objects (state API). Filters
        through contains() so native-arena LRU evictions the directory
        hasn't observed yet are not reported."""
        with self._lock:
            resident = list(self._objects.items())[:limit]
            spilled = list(self._spilled.items())[:max(0, limit - len(resident))]
            pins = set(self._pins)
            replayable = {h for h, info in self._lineage_info.items()
                          if info.get("replayable")}
        rows = [
            {"object_id": h, "size_bytes": size, "pinned": h in pins,
             "spilled": False, "tier": "shm", "lineage": h in replayable}
            for h, size in resident if self.contains(h)
        ]
        rows += [
            {"object_id": h, "size_bytes": size, "pinned": False,
             "spilled": True, "tier": "disk", "lineage": h in replayable}
            for h, size in spilled
        ]
        with self._lock:
            remote = list(self._remote.items())[:max(0, limit - len(rows))]
        rows += [
            {"object_id": h, "size_bytes": size, "pinned": False,
             "spilled": True, "tier": "remote"}
            for h, size in remote
        ]
        return rows

    def contains(self, object_id_hex: str) -> bool:
        # remote-tier objects are NOT local: a False here is what routes
        # waiters back into the pull plane (the remote tier's restore)
        if self.native:
            # the C++ arena is authoritative — it may have LRU-evicted the
            # object without telling us, and a stale True here would make
            # the agent skip a remote pull for a locally-lost object
            return self.client.contains(ObjectID.from_hex(object_id_hex))
        with self._lock:
            return object_id_hex in self._objects or object_id_hex in self._spilled

    def is_spilled(self, object_id_hex: str) -> bool:
        with self._lock:
            return object_id_hex in self._spilled

    def spill_tier(self, object_id_hex: str) -> Optional[str]:
        with self._lock:
            if object_id_hex in self._objects:
                return "shm"
            if object_id_hex in self._spilled:
                return "disk"
            if object_id_hex in self._remote:
                return "remote"
            return None

    def delete(self, object_id_hex: str) -> None:
        with self._lock:
            size = self._objects.pop(object_id_hex, None)
            if size is not None:
                oid = ObjectID.from_hex(object_id_hex)
                deleted = self.client.delete(oid)
                if self.native and not deleted and self.client.contains(oid):
                    # a reader's pin refused the arena delete (still
                    # present): retry once the view dies. A not-found
                    # refusal (arena LRU already took it) needs nothing.
                    self._deferred_deletes.add(object_id_hex)
                self.used -= size
            if object_id_hex in self._spilled:
                self._spilled.pop(object_id_hex)
                try:
                    os.unlink(os.path.join(self.spill_dir, object_id_hex))
                except OSError:
                    pass
            self._remote.pop(object_id_hex, None)
            self._remote_sources.pop(object_id_hex, None)
            self._lineage_info.pop(object_id_hex, None)
            self._pins.pop(object_id_hex, None)
            if self.native and self._native_pins.pop(
                    object_id_hex, None) is not None:
                self.client.release(ObjectID.from_hex(object_id_hex))

    def stats(self) -> Dict:
        self._retry_deferred_deletes()
        if self.native:
            # arena-side numbers are authoritative (incl. its own evictions)
            st = dict(self.client.stats())
            with self._lock:
                st["num_spilled"] = len(self._spilled)
                st["num_spills"] = self.num_spills
                # deletes a reader pin refused (bytes still in the arena
                # until the view dies): the first place to look when
                # arena used > directory bytes — a leaked view upstream
                st["deferred_deletes"] = sorted(self._deferred_deletes)
            return st
        with self._lock:
            return {
                "used": self.used,
                "capacity": self.capacity,
                "num_objects": len(self._objects),
                "num_spilled": len(self._spilled),
                "num_evictions": self.num_evictions,
                "num_spills": self.num_spills,
            }

    def tier_stats(self) -> Dict:
        """Spill-tier breakdown (GetPullStats / CLI status / bench)."""
        self._retry_deferred_deletes()
        if self.native:
            shm_bytes = int(self.client.stats().get("used", 0))
        else:
            with self._lock:
                shm_bytes = sum(self._objects.values())
        with self._lock:
            return {
                "shm_bytes": shm_bytes,
                "shm_objects": len(self._objects),
                "disk_objects": len(self._spilled),
                "disk_bytes": sum(self._spilled.values()),
                "remote_objects": len(self._remote),
                "remote_bytes": sum(self._remote.values()),
                "objects_with_remote_sources": len(self._remote_sources),
                "num_spills": self.num_spills,
                "num_restores": self.num_restores,
                "num_remote_demotions": self.num_remote_demotions,
                "num_evictions": self.num_evictions,
                "num_lineage_evictions": self.num_lineage_evictions,
                "lineage_hinted_objects": len(self._lineage_info),
            }

    # -- eviction / tiered spilling ------------------------------------------
    def _ensure_space(self, size: int) -> None:
        """Make `size` fit, walking the tiers: evict unpinned (owner-
        recoverable) → spill pinned primaries to disk → demote objects
        with a recorded remote holder to the remote tier. Caller holds
        the lock."""
        if self.native:
            return  # C++ arena evicts internally
        if size > self.capacity:
            raise ObjectStoreFullError(
                f"object of size {size} exceeds store capacity {self.capacity}"
            )
        while self.used + size > self.capacity:
            victim = self._pick_victim()
            if victim is not None:
                vsize = self._objects.pop(victim)
                self.client.delete(ObjectID.from_hex(victim))
                self.used -= vsize
                self.num_evictions += 1
                if self._lineage_info.get(victim, {}).get("replayable"):
                    self.num_lineage_evictions += 1
                continue
            # Everything is pinned: spill the oldest pinned object to disk.
            if any(self._spill(hex_id) for hex_id in list(self._objects)):
                continue
            # Disk tier unavailable (write failure / dir gone): drop the
            # oldest object with a known remote holder — the pull plane
            # restores it on demand.
            if any(self._demote_remote(hex_id)
                   for hex_id in list(self._objects)):
                continue
            # Final tier (ISSUE 17): drop a copy with NO remote holder
            # but a live replayable lineage record — its owner rebuilds
            # it by task replay when the next pull misses.
            if any(self._drop_lineage_backed(hex_id)
                   for hex_id in list(self._objects)):
                continue
            raise ObjectStoreFullError(
                f"store full ({self.used}/{self.capacity}) and nothing can "
                "be evicted, spilled, demoted to a remote holder, or "
                "dropped against a replayable lineage record"
            )

    # bounded preference window: scanning the whole LRU per eviction
    # would make eviction O(n^2) under churn
    _LINEAGE_SCAN = 32

    def _pick_victim(self) -> Optional[str]:
        """Next shm eviction victim (caller holds the lock): LRU order,
        but within a bounded window an unpinned copy whose lineage record
        is live and CHEAP to replay (lowest exec-EMA) is preferred over
        expensive or lineage-less copies (ISSUE 17) — losing it costs one
        fast task replay instead of the object."""
        first = None
        best = None
        best_ms = 0.0
        scanned = 0
        for hex_id in self._objects:  # oldest first
            if self._pins.get(hex_id, 0):
                continue
            if first is None:
                first = hex_id
            info = self._lineage_info.get(hex_id)
            if info is not None and info.get("replayable"):
                ms = float(info.get("exec_ms", 0.0))
                if best is None or ms < best_ms:
                    best, best_ms = hex_id, ms
            scanned += 1
            if scanned >= self._LINEAGE_SCAN:
                break
        return best if best is not None else first

    def _drop_lineage_backed(self, object_id_hex: str) -> bool:
        """Last-resort demotion: delete a (possibly pinned) shm copy that
        has no remote holder but IS rebuildable by its owner's lineage
        replay. Memory-safe for pinned objects on the tmpfs backend (live
        mmaps outlive the unlink), exactly like ``_demote_remote``."""
        if self.native:
            return False
        info = self._lineage_info.get(object_id_hex)
        if not info or not info.get("replayable"):
            return False
        size = self._objects.pop(object_id_hex, None)
        if size is None:
            return False
        self.client.delete(ObjectID.from_hex(object_id_hex))
        self.used -= size
        self.num_lineage_evictions += 1
        return True

    def _spill(self, object_id_hex: str) -> bool:
        if self.native:
            return False  # native backend relies on in-arena LRU eviction
        view = self.client.get_view(ObjectID.from_hex(object_id_hex))
        if view is None:
            self.used -= self._objects.pop(object_id_hex, 0)
            return False
        try:
            os.makedirs(self.spill_dir, exist_ok=True)
            path = os.path.join(self.spill_dir, object_id_hex)
            with open(path, "wb") as f:
                f.write(view)
        except OSError:
            # disk tier unavailable: the caller's next tier (remote
            # demotion) may still make room
            return False
        size = self._objects.pop(object_id_hex)
        self.client.delete(ObjectID.from_hex(object_id_hex))
        self.used -= size
        self._spilled[object_id_hex] = size
        self.num_spills += 1
        self._enforce_disk_cap()
        return True

    def _demote_remote(self, object_id_hex: str) -> bool:
        """Drop the local (shm) copy against a recorded remote holder.
        Memory-safe even for pinned objects on the tmpfs backend (live
        mmaps outlive the unlink); only taken when the disk tier cannot."""
        if self.native or not self._remote_sources.get(object_id_hex):
            return False
        size = self._objects.pop(object_id_hex, None)
        if size is None:
            return False
        self.client.delete(ObjectID.from_hex(object_id_hex))
        self.used -= size
        self._remote[object_id_hex] = size
        self.num_remote_demotions += 1
        return True

    def _enforce_disk_cap(self) -> None:
        """Keep the disk tier under ``object_spill_disk_max_bytes`` by
        demoting its OLDEST entries with a known remote holder (drop the
        file, keep the record). Entries without a source may still go if
        a live replayable lineage record backs them (ISSUE 17: the owner
        replays the producing task on the next failed pull); everything
        else stays — it is the only copy."""
        cap = CONFIG.object_spill_disk_max_bytes
        if not cap:
            return
        disk_bytes = sum(self._spilled.values())
        for hex_id in list(self._spilled):
            if disk_bytes <= cap:
                break
            if not self._remote_sources.get(hex_id):
                info = self._lineage_info.get(hex_id)
                if not (info and info.get("replayable")):
                    continue
                size = self._spilled.pop(hex_id)
                try:
                    os.unlink(os.path.join(self.spill_dir, hex_id))
                except OSError:
                    pass
                self.num_lineage_evictions += 1
                disk_bytes -= size
                continue
            size = self._spilled.pop(hex_id)
            try:
                os.unlink(os.path.join(self.spill_dir, hex_id))
            except OSError:
                pass
            self._remote[hex_id] = size
            self.num_remote_demotions += 1
            disk_bytes -= size

    def restore(self, object_id_hex: str) -> bool:
        """Bring a spilled object back into shm, streaming the file through
        ``create()``/``seal()`` in chunks — a whole-file ``read()`` held the
        object twice (bytes blob + store copy), so restoring a
        near-capacity object doubled peak memory exactly when the store
        was under the most pressure."""
        with self._lock:
            if object_id_hex in self._objects:
                return True
            size = self._spilled.get(object_id_hex)
            if size is None:
                return False
            path = os.path.join(self.spill_dir, object_id_hex)
            self._ensure_space(size)
            oid = ObjectID.from_hex(object_id_hex)
            view, handle = self.client.create(oid, size)
            chunk = max(1, CONFIG.object_chunk_size_bytes)
            try:
                with open(path, "rb") as f:
                    off = 0
                    while off < size:
                        n = f.readinto(view[off:off + min(chunk, size - off)])
                        if not n:
                            raise IOError(
                                f"spilled object {object_id_hex} truncated "
                                f"at {off}/{size} bytes")
                        off += n
            except Exception:
                self.client.abort(handle)
                raise
            self.client.seal(oid, handle)
            self._objects[object_id_hex] = size
            self.used += size
            self._spilled.pop(object_id_hex)
            self.num_restores += 1
            os.unlink(path)
            return True

    def read_maybe_spilled(self, object_id_hex: str) -> Optional[memoryview]:
        view = self.client.get_view(ObjectID.from_hex(object_id_hex))
        if view is not None:
            self.touch(object_id_hex)
            return view
        if self.restore(object_id_hex):
            return self.client.get_view(ObjectID.from_hex(object_id_hex))
        return None
