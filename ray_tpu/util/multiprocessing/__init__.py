"""``multiprocessing.Pool`` shim over cluster tasks (reference:
python/ray/util/multiprocessing/pool.py — Pool on actors so existing
Pool-based code scales past one machine unchanged)."""

from ray_tpu.util.multiprocessing.pool import Pool

__all__ = ["Pool"]
