"""Structured cluster event log (reference: src/ray/util/event.h:41 —
RAY_EVENT macros write severity-tagged JSON event files that the dashboard
event module aggregates; VERDICT r1 missing #9).

Each process appends JSON lines to its own file under
``<session>/logs/events/``; readers (state API, dashboard) scan the
directory. Emission never throws — an observability path must not take
down the component it observes.
"""

from __future__ import annotations

import json
import os
import time
from typing import Any, Dict, List, Optional

SEVERITIES = ("DEBUG", "INFO", "WARNING", "ERROR", "FATAL")

_writer: Optional["_EventWriter"] = None


class _EventWriter:
    def __init__(self, session_dir: str, component: str):
        self.dir = os.path.join(session_dir, "logs", "events")
        os.makedirs(self.dir, exist_ok=True)
        self.path = os.path.join(
            self.dir, f"event_{component}_{os.getpid()}.log")
        self.component = component

    def write(self, record: Dict) -> None:
        with open(self.path, "a") as f:
            f.write(json.dumps(record, default=str) + "\n")


def init_event_log(session_dir: str, component: str) -> None:
    """Called once per process (head/agent/driver) at startup."""
    global _writer
    try:
        _writer = _EventWriter(session_dir, component)
    except Exception:
        _writer = None


def report_event(severity: str, label: str, message: str,
                 **fields: Any) -> None:
    """Append one structured event (reference: RAY_EVENT(severity, label)
    << message). No-op before init_event_log / on any IO failure."""
    if _writer is None:
        return
    try:
        _writer.write({
            "timestamp": time.time(),
            "severity": severity if severity in SEVERITIES else "INFO",
            "label": label,
            "message": message,
            "component": _writer.component,
            "pid": os.getpid(),
            **fields,
        })
    except Exception:
        pass


def read_events(session_dir: str, *, severity: Optional[str] = None,
                label: Optional[str] = None,
                limit: int = 1000) -> List[Dict]:
    """All events recorded in a session, newest last."""
    events_dir = os.path.join(session_dir, "logs", "events")
    out: List[Dict] = []
    try:
        names = sorted(os.listdir(events_dir))
    except FileNotFoundError:
        return out
    for name in names:
        if not name.startswith("event_"):
            continue
        try:
            with open(os.path.join(events_dir, name)) as f:
                for line in f:
                    try:
                        out.append(json.loads(line))
                    except json.JSONDecodeError:
                        continue
        except OSError:
            continue
    if severity:
        out = [e for e in out if e.get("severity") == severity]
    if label:
        out = [e for e in out if e.get("label") == label]
    out.sort(key=lambda e: e.get("timestamp", 0.0))
    return out[-limit:]
