"""Placement group + scheduling strategy tests.

Modeled on the reference's python/ray/tests/test_placement_group*.py:
create/wait/remove, strategies, bundle-targeted tasks and actors, pending
groups becoming ready when capacity frees up.
"""

import pytest

import ray_tpu
from ray_tpu.util.placement_group import (
    PlacementGroup, placement_group, placement_group_table,
    remove_placement_group)
from ray_tpu.util.scheduling_strategies import (
    NodeLabelSchedulingStrategy, PlacementGroupSchedulingStrategy)


def test_pg_create_wait_remove(ray_cluster_2):
    pg = placement_group([{"CPU": 1}, {"CPU": 1}], strategy="SPREAD")
    assert pg.wait(timeout_seconds=10)
    table = placement_group_table(pg)[pg.id_hex]
    assert table["state"] == "CREATED"
    assert len(set(table["placement"])) == 2  # spread across both nodes
    remove_placement_group(pg)
    table = placement_group_table(pg)[pg.id_hex]
    assert table["state"] == "REMOVED"


def test_pg_strict_pack_single_node(ray_cluster_2):
    pg = placement_group([{"CPU": 1}, {"CPU": 1}], strategy="STRICT_PACK")
    assert pg.wait(timeout_seconds=10)
    table = placement_group_table(pg)[pg.id_hex]
    assert len(set(table["placement"])) == 1
    remove_placement_group(pg)


def test_pg_task_runs_in_bundle(ray_cluster_2):
    pg = placement_group([{"CPU": 1}], strategy="PACK")
    assert pg.wait(timeout_seconds=10)
    target = placement_group_table(pg)[pg.id_hex]["placement"][0]

    @ray_tpu.remote
    def where():
        return ray_tpu.get_runtime_context().get_node_id()

    node = ray_tpu.get(where.options(
        scheduling_strategy=PlacementGroupSchedulingStrategy(
            placement_group=pg, placement_group_bundle_index=0),
        num_cpus=1,
    ).remote())
    assert node == target
    remove_placement_group(pg)


def test_pg_actor_in_bundle(ray_cluster_2):
    pg = placement_group([{"CPU": 1}], strategy="PACK")
    assert pg.wait(timeout_seconds=10)
    target = placement_group_table(pg)[pg.id_hex]["placement"][0]

    @ray_tpu.remote
    class A:
        def where(self):
            return ray_tpu.get_runtime_context().get_node_id()

    a = A.options(
        scheduling_strategy=PlacementGroupSchedulingStrategy(
            placement_group=pg, placement_group_bundle_index=0),
        num_cpus=1,
    ).remote()
    assert ray_tpu.get(a.where.remote()) == target
    ray_tpu.kill(a)
    remove_placement_group(pg)


def test_pg_infeasible_stays_pending_then_ready(ray_cluster_2):
    # Ask for more CPU than any node has; stays PENDING.
    pg = placement_group([{"CPU": 1000}], strategy="PACK")
    assert not pg.wait(timeout_seconds=0.5)
    table = placement_group_table(pg)[pg.id_hex]
    assert table["state"] == "PENDING"
    remove_placement_group(pg)


def test_pg_pending_becomes_created_after_release(ray_cluster_2):
    # Reserve all CPU on both nodes, then a new PG must wait until removal.
    each = ray_tpu.cluster_resources().get("CPU", 0) / 2
    first = placement_group([{"CPU": each}, {"CPU": each}], strategy="SPREAD")
    assert first.wait(timeout_seconds=10)
    second = placement_group([{"CPU": 1}], strategy="PACK")
    assert not second.wait(timeout_seconds=0.5)
    remove_placement_group(first)
    assert second.wait(timeout_seconds=10)
    remove_placement_group(second)


def test_pg_validation(ray_cluster_2):
    with pytest.raises(ValueError):
        placement_group([], strategy="PACK")
    with pytest.raises(ValueError):
        placement_group([{"CPU": 1}], strategy="BOGUS")
    with pytest.raises(ValueError):
        placement_group([{"CPU": -1}])


def test_pg_empty_handle():
    assert PlacementGroup.empty().is_empty


def test_task_on_removed_pg_fails(ray_cluster_2):
    pg = placement_group([{"CPU": 1}])
    assert pg.wait(timeout_seconds=10)
    remove_placement_group(pg)

    @ray_tpu.remote
    def f():
        return 1

    ref = f.options(
        scheduling_strategy=PlacementGroupSchedulingStrategy(
            placement_group=pg, placement_group_bundle_index=0),
        num_cpus=1,
    ).remote()
    with pytest.raises(RuntimeError, match="removed"):
        ray_tpu.get(ref, timeout=20)


def test_pg_lease_returns_to_bundle_agent(ray_cluster_2):
    """Bundle resources must be repaid after tasks finish (lease returned to
    the agent holding the bundle, not the driver's local agent)."""
    pg = placement_group([{"CPU": 1}])
    assert pg.wait(timeout_seconds=10)

    @ray_tpu.remote
    def one():
        return 1

    strat = PlacementGroupSchedulingStrategy(placement_group=pg,
                                             placement_group_bundle_index=0)
    # Serial rounds: each needs the full bundle back. With a lease-return
    # bug the second round would hang on an exhausted bundle pool.
    for _ in range(3):
        assert ray_tpu.get(
            one.options(scheduling_strategy=strat, num_cpus=1).remote(),
            timeout=30) == 1
        import time

        time.sleep(0.5)  # let idle lease TTL return the bundle
    remove_placement_group(pg)


def test_named_pg_bundle_specs_roundtrip(ray_cluster_2):
    from ray_tpu.util.placement_group import get_placement_group

    pg = placement_group([{"CPU": 1.5}], name="specs_pg")
    assert pg.wait(timeout_seconds=10)
    got = get_placement_group("specs_pg")
    assert got.bundle_specs == [{"CPU": 1.5}]
    remove_placement_group(pg)


def test_pg_default_bundle_index_any(ray_cluster_2):
    """bundle_index defaults to -1 = any bundle (reference semantics)."""
    pg = placement_group([{"CPU": 1}, {"CPU": 1}], strategy="SPREAD")
    assert pg.wait(timeout_seconds=10)
    nodes = set(placement_group_table(pg)[pg.id_hex]["placement"])

    @ray_tpu.remote
    def where():
        return ray_tpu.get_runtime_context().get_node_id()

    out = ray_tpu.get([
        where.options(
            scheduling_strategy=PlacementGroupSchedulingStrategy(
                placement_group=pg),
            num_cpus=1,
        ).remote()
        for _ in range(4)
    ], timeout=30)
    assert set(out) <= nodes

    @ray_tpu.remote
    class A:
        def where(self):
            return ray_tpu.get_runtime_context().get_node_id()

    a = A.options(
        scheduling_strategy=PlacementGroupSchedulingStrategy(
            placement_group=pg),
        num_cpus=1,
    ).remote()
    assert ray_tpu.get(a.where.remote()) in nodes
    ray_tpu.kill(a)
    remove_placement_group(pg)


def test_get_current_placement_group_inside_task(ray_cluster_2):
    from ray_tpu.util.placement_group import get_current_placement_group

    pg = placement_group([{"CPU": 1}], strategy="PACK")
    assert pg.wait(timeout_seconds=10)

    @ray_tpu.remote
    def current():
        cur = get_current_placement_group()
        return cur.id_hex if cur else None

    got = ray_tpu.get(current.options(
        scheduling_strategy=PlacementGroupSchedulingStrategy(
            placement_group=pg, placement_group_bundle_index=0),
        num_cpus=1,
    ).remote(), timeout=30)
    assert got == pg.id_hex
    # outside any PG
    assert ray_tpu.get(current.remote(), timeout=30) is None
    remove_placement_group(pg)


def test_queued_pg_lease_fails_on_remove(ray_cluster_2):
    """A lease queued on a full bundle must fail (not hang) when the PG is
    removed."""
    import time

    pg = placement_group([{"CPU": 1}], strategy="PACK")
    assert pg.wait(timeout_seconds=10)

    @ray_tpu.remote
    def hold(sec):
        time.sleep(sec)
        return "held"

    strat = PlacementGroupSchedulingStrategy(
        placement_group=pg, placement_group_bundle_index=0)
    blocker = hold.options(scheduling_strategy=strat, num_cpus=1).remote(3)
    time.sleep(0.5)  # let it occupy the bundle
    queued = hold.options(scheduling_strategy=strat, num_cpus=1).remote(0)
    time.sleep(0.3)
    remove_placement_group(pg)
    with pytest.raises(Exception):
        ray_tpu.get(queued, timeout=15)
