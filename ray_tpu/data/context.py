"""DataContext — per-process execution configuration (reference:
python/ray/data/context.py DataContext / DatasetContext: a thread-safe
singleton of tunables read by the planner and streaming executor).
"""

from __future__ import annotations

import dataclasses
import threading
from typing import ClassVar, Optional


@dataclasses.dataclass
class DataContext:
    """Knobs for the streaming execution engine.

    - ``read_parallelism``: default number of read tasks per datasource
    - ``max_tasks_in_flight_per_op``: bounded concurrent tasks per map op
    - ``per_op_buffer``: bundles buffered between operators (backpressure)
    - ``output_buffer``: bundles buffered at the consumer edge
    """

    read_parallelism: int = 8
    max_tasks_in_flight_per_op: int = 8
    per_op_buffer: int = 32
    output_buffer: int = 16
    # bytes of queued block payload the pipeline may hold before dispatch
    # is restricted to the most-downstream op (0 = unlimited); enforced by
    # ResourceBudgetBackpressurePolicy via the ResourceManager
    execution_memory_limit: int = 0
    # policy classes consulted on every dispatch (None = defaults:
    # concurrency cap, streaming output buffer, resource budget)
    backpressure_policies: Optional[list] = None

    _lock: ClassVar[threading.Lock] = threading.Lock()
    _current: ClassVar[Optional["DataContext"]] = None

    @classmethod
    def get_current(cls) -> "DataContext":
        with cls._lock:
            if cls._current is None:
                cls._current = cls()
            return cls._current

    @classmethod
    def _set_current(cls, ctx: "DataContext") -> None:
        with cls._lock:
            cls._current = ctx
