"""Collective API across actors/tasks (reference:
python/ray/util/collective/collective.py — GroupManager :40,
init_collective_group :120, create_collective_group :151, allreduce :258,
barrier :298, reduce :311, broadcast :373, allgather :423,
reducescatter :472).

Groups are process-local objects registered in a ``GroupManager``; rendezvous
and declarative group creation ride the head's internal KV + a named store
actor instead of NCCL uniqueId broadcast.
"""

from __future__ import annotations

import json
import threading
from typing import Any, Dict, List, Optional

from ray_tpu.util.collective.types import (
    AllGatherOptions, AllReduceOptions, BarrierOptions, BroadcastOptions,
    Backend, RecvOptions, ReduceOp, ReduceOptions, ReduceScatterOptions,
    SendOptions)

_DECL_NS = "collective"


class GroupManager:
    """Process-local registry of collective groups (reference:
    collective.py:40)."""

    def __init__(self):
        self._groups: Dict[str, Any] = {}
        self._lock = threading.Lock()

    def create_group(self, backend: Backend, world_size: int, rank: int,
                     group_name: str, store_key: str = ""):
        from ray_tpu.util.collective.collective_group.cpu_group import CPUGroup
        from ray_tpu.util.collective.collective_group.xla_group import XLAGroup

        cls = XLAGroup if backend == Backend.XLA else CPUGroup
        with self._lock:
            if group_name in self._groups:
                raise RuntimeError(
                    f"Collective group {group_name!r} already initialized in "
                    f"this process")
            g = cls(world_size, rank, group_name, store_key)
            self._groups[group_name] = g
            return g

    def get_group(self, group_name: str):
        with self._lock:
            g = self._groups.get(group_name)
        if g is None:
            g = self._try_declared_init(group_name)
        if g is None:
            raise RuntimeError(
                f"Collective group {group_name!r} is not initialized in this "
                f"process; call init_collective_group() or "
                f"create_collective_group() first")
        return g

    def destroy_group(self, group_name: str):
        with self._lock:
            g = self._groups.pop(group_name, None)
        if g is not None:
            g.destroy_group()

    def _try_declared_init(self, group_name: str):
        """Lazy init from a declaration written by create_collective_group
        (reference: declarative path collective.py:151)."""
        import ray_tpu
        from ray_tpu._private.worker import KvClient, global_worker

        if global_worker is None or not global_worker.connected:
            return None
        kv = KvClient(global_worker)
        raw = kv.get(f"decl:{group_name}".encode(), namespace=_DECL_NS)
        if raw is None:
            return None
        decl = json.loads(raw.decode())
        my_actor = ray_tpu.get_runtime_context().get_actor_id()
        rank = decl["ranks"].get(my_actor or "")
        if rank is None:
            return None
        try:
            return self.create_group(
                Backend.coerce(decl["backend"]), decl["world_size"], rank,
                group_name)
        except RuntimeError:
            # Lost a same-process race to another thread's lazy init.
            with self._lock:
                return self._groups.get(group_name)


_group_mgr = GroupManager()


def is_group_initialized(group_name: str = "default") -> bool:
    try:
        _group_mgr.get_group(group_name)
        return True
    except RuntimeError:
        return False


def init_collective_group(world_size: int, rank: int,
                          backend: str = "xla",
                          group_name: str = "default",
                          store_key: str = ""):
    """Initialize this process's membership in a collective group
    (reference: collective.py:120). Call once per member, same order args."""
    if not (0 <= rank < world_size):
        raise ValueError(f"rank {rank} out of range [0, {world_size})")
    return _group_mgr.create_group(
        Backend.coerce(backend), world_size, rank, group_name, store_key)


def create_collective_group(actors: List[Any], world_size: int,
                            ranks: List[int], backend: str = "xla",
                            group_name: str = "default") -> None:
    """Declarative group creation from the driver (reference:
    collective.py:151): writes the membership table to the head KV; each
    actor's first collective op lazily joins with its declared rank."""
    if len(actors) != world_size or sorted(ranks) != list(range(world_size)):
        raise ValueError("need exactly world_size actors with ranks 0..n-1")
    from ray_tpu._private.worker import KvClient, global_worker

    decl = {
        "backend": str(Backend.coerce(backend).value),
        "world_size": world_size,
        "ranks": {a._actor_id.hex(): r for a, r in zip(actors, ranks)},
    }
    KvClient(global_worker).put(
        f"decl:{group_name}".encode(), json.dumps(decl).encode(),
        namespace=_DECL_NS)


def destroy_collective_group(group_name: str = "default") -> None:
    _group_mgr.destroy_group(group_name)


def get_rank(group_name: str = "default") -> int:
    return _group_mgr.get_group(group_name).rank


def get_collective_group_size(group_name: str = "default") -> int:
    return _group_mgr.get_group(group_name).world_size


def allreduce(tensor, group_name: str = "default",
              op: ReduceOp = ReduceOp.SUM):
    return _group_mgr.get_group(group_name).allreduce(
        tensor, AllReduceOptions(reduceOp=op))


def barrier(group_name: str = "default") -> None:
    _group_mgr.get_group(group_name).barrier(BarrierOptions())


def reduce(tensor, dst_rank: int = 0, group_name: str = "default",
           op: ReduceOp = ReduceOp.SUM):
    return _group_mgr.get_group(group_name).reduce(
        tensor, ReduceOptions(reduceOp=op, root_rank=dst_rank))


def broadcast(tensor, src_rank: int = 0, group_name: str = "default"):
    return _group_mgr.get_group(group_name).broadcast(
        tensor, BroadcastOptions(root_rank=src_rank))


def allgather(tensor, group_name: str = "default") -> List[Any]:
    return _group_mgr.get_group(group_name).allgather(
        tensor, AllGatherOptions())


def reducescatter(tensor_list, group_name: str = "default",
                  op: ReduceOp = ReduceOp.SUM):
    return _group_mgr.get_group(group_name).reducescatter(
        tensor_list, ReduceScatterOptions(reduceOp=op))


def send(tensor, dst_rank: int, group_name: str = "default") -> None:
    _group_mgr.get_group(group_name).send(tensor, SendOptions(dst_rank=dst_rank))


def recv(like, src_rank: int, group_name: str = "default"):
    """Receive a tensor; ``like`` supplies dtype/placement (may be None)."""
    return _group_mgr.get_group(group_name).recv(
        like, RecvOptions(src_rank=src_rank))


def allreduce_sharded(tensor, mesh, axis: str, group_name: str = "default",
                      op: ReduceOp = ReduceOp.SUM):
    """TPU-native hierarchical allreduce: ICI psum over the member's local
    mesh axis, then cross-member combine (multigpu-variant analog)."""
    g = _group_mgr.get_group(group_name)
    if not hasattr(g, "allreduce_sharded"):
        raise RuntimeError("allreduce_sharded requires the XLA backend")
    return g.allreduce_sharded(tensor, mesh, axis, op)
