"""DDPG + TD3 — deterministic-policy-gradient continuous control
(reference: rllib/algorithms/ddpg/ddpg.py and td3.py, externalized to
rllib_contrib in the snapshot; Lillicrap 2015, Fujimoto 2018).

One module/learner pair covers both: TD3 is DDPG with (a) twin critics
taking the min for the target, (b) target-policy smoothing noise, and
(c) delayed actor updates — all config flags here, defaulted per paper in
``TD3Config``. Target networks for actor and critics use polyak averaging.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax

import ray_tpu
from ray_tpu.rllib.algorithms.algorithm import Algorithm
from ray_tpu.rllib.algorithms.algorithm_config import AlgorithmConfig
from ray_tpu.rllib.utils.replay_buffer import ReplayBuffer


# ------------------------------------------------------------------- module
@dataclasses.dataclass
class DDPGModuleSpec:
    obs_dim: int
    action_dim: int
    discrete: bool = False
    hiddens: Tuple[int, ...] = (256, 256)
    activation: str = "relu"
    exploration_noise: float = 0.1  # sigma of the behavior Gaussian

    def build(self) -> "DDPGModule":
        return DDPGModule(self)


class DDPGModule:
    """tanh deterministic actor + twin Q towers (the second tower is
    ignored when twin_q=False)."""

    def __init__(self, spec: DDPGModuleSpec):
        self.spec = spec
        self._act = {"tanh": jnp.tanh, "relu": jax.nn.relu}[spec.activation]

    def _mlp(self, key, sizes):
        layers = []
        for a, b in zip(sizes[:-1], sizes[1:]):
            key, sub = jax.random.split(key)
            layers.append({
                "w": jax.random.normal(sub, (a, b)) * jnp.sqrt(2.0 / a),
                "b": jnp.zeros((b,)),
            })
        return layers

    def init(self, rng) -> Dict:
        ka, k1, k2 = jax.random.split(rng, 3)
        h = self.spec.hiddens
        obs, act = self.spec.obs_dim, self.spec.action_dim
        return {
            "actor": self._mlp(ka, (obs, *h, act)),
            "q1": self._mlp(k1, (obs + act, *h, 1)),
            "q2": self._mlp(k2, (obs + act, *h, 1)),
        }

    def _tower(self, layers, x):
        for layer in layers[:-1]:
            x = self._act(x @ layer["w"] + layer["b"])
        last = layers[-1]
        return x @ last["w"] + last["b"]

    def pi(self, params, obs):
        return jnp.tanh(self._tower(params["actor"], obs))

    def q(self, params, obs, action):
        x = jnp.concatenate([obs, action], axis=-1)
        return (self._tower(params["q1"], x)[..., 0],
                self._tower(params["q2"], x)[..., 0])

    # env-runner interface
    def forward(self, params, obs) -> Dict[str, jnp.ndarray]:
        action = self.pi(params, obs)
        q1, _ = self.q(params, obs, action)
        return {"logits": action, "vf": q1}

    def explore_action(self, params, obs, rng):
        a = self.pi(params, obs)
        noise = self.spec.exploration_noise * \
            jax.random.normal(rng, a.shape)
        a = jnp.clip(a + noise, -1.0, 1.0)
        q1, _ = self.q(params, obs, a)
        return a, jnp.zeros(a.shape[:-1]), q1

    def greedy_action(self, params, obs):
        a = self.pi(params, obs)
        q1, _ = self.q(params, obs, a)
        return a, jnp.zeros(a.shape[:-1]), q1


# ------------------------------------------------------------------ learner
class DDPGLearner:
    """Critic TD step + (possibly delayed) deterministic actor step
    (Learner duck-type like SACLearner)."""

    def __init__(self, module_spec: DDPGModuleSpec, config: Dict,
                 use_mesh: bool = True):
        self.module = module_spec.build()
        self.config = config
        self._rng = jax.random.key(config.get("seed", 0))
        self._rng, init_key = jax.random.split(self._rng)
        self.params = self.module.init(init_key)
        self.target_params = jax.tree.map(jnp.copy, self.params)
        self.tx = optax.adam(config.get("lr", 1e-3))
        self.opt_state = self.tx.init(self.params)
        self._n_updates = 0
        self._update = self._build_update()

    def _build_update(self):
        cfg = self.config
        gamma = cfg.get("gamma", 0.99)
        tau = cfg.get("tau", 0.005)
        twin_q = cfg.get("twin_q", False)
        smooth = cfg.get("target_noise", 0.0)
        noise_clip = cfg.get("noise_clip", 0.5)

        def critic_loss(params, target_params, batch, key):
            next_a = self.module.pi(target_params, batch["next_obs"])
            if smooth > 0:
                eps = jnp.clip(
                    smooth * jax.random.normal(key, next_a.shape),
                    -noise_clip, noise_clip)
                next_a = jnp.clip(next_a + eps, -1.0, 1.0)
            tq1, tq2 = self.module.q(target_params, batch["next_obs"],
                                     next_a)
            q_next = jnp.minimum(tq1, tq2) if twin_q else tq1
            target = jax.lax.stop_gradient(
                batch["rewards"] + gamma * (1 - batch["dones"]) * q_next)
            q1, q2 = self.module.q(params, batch["obs"], batch["actions"])
            loss = jnp.mean((q1 - target) ** 2)
            if twin_q:
                loss = loss + jnp.mean((q2 - target) ** 2)
            return loss, {"critic_loss": loss, "qf_mean": jnp.mean(q1)}

        def actor_loss(params, batch):
            a = self.module.pi(params, batch["obs"])
            q1, _ = self.module.q(jax.lax.stop_gradient(params),
                                  batch["obs"], a)
            return -jnp.mean(q1)

        def update(params, target_params, opt_state, batch, rng,
                   do_actor):
            rng, key = jax.random.split(rng)
            (_, metrics), c_grads = jax.value_and_grad(
                critic_loss, has_aux=True)(params, target_params, batch,
                                           key)
            a_loss, a_grads = jax.value_and_grad(actor_loss)(params, batch)
            # delayed policy update: zero the actor grads on off ticks
            # (static branch would recompile; a where keeps one program)
            scale = jnp.where(do_actor, 1.0, 0.0)
            grads = {
                "actor": jax.tree.map(lambda g: g * scale,
                                      a_grads["actor"]),
                "q1": c_grads["q1"], "q2": c_grads["q2"],
            }
            updates, opt_state = self.tx.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            target_params = jax.tree.map(
                lambda t, o: (1 - tau) * t + tau * o, target_params, params)
            metrics["actor_loss"] = a_loss
            return params, target_params, opt_state, metrics, rng

        return jax.jit(update)

    def update(self, batch: Dict[str, np.ndarray]) -> Dict[str, float]:
        self._n_updates += 1
        delay = self.config.get("policy_delay", 1)
        do_actor = (self._n_updates % delay) == 0
        self.params, self.target_params, self.opt_state, metrics, \
            self._rng = self._update(self.params, self.target_params,
                                     self.opt_state, batch, self._rng,
                                     do_actor)
        return {k: float(v) for k, v in metrics.items()}

    # Learner duck-type
    def get_weights(self):
        return jax.device_get(self.params)

    def set_weights(self, weights) -> None:
        self.params = jax.device_put(weights)

    def get_state(self) -> Dict:
        return {"params": jax.device_get(self.params),
                "target_params": jax.device_get(self.target_params),
                "opt_state": jax.device_get(self.opt_state),
                "n_updates": self._n_updates}

    def set_state(self, state: Dict) -> None:
        self.params = state["params"]
        self.target_params = state["target_params"]
        self.opt_state = state["opt_state"]
        self._n_updates = state.get("n_updates", 0)


# ---------------------------------------------------------------- algorithm
class DDPGConfig(AlgorithmConfig):
    def __init__(self, algo_class=None):
        super().__init__(algo_class=algo_class or DDPG)
        self.lr = 1e-3
        self.train_batch_size = 256
        self.replay_buffer_capacity = 100_000
        self.num_steps_sampled_before_learning_starts = 1500
        self.tau = 0.005
        self.twin_q = False
        self.policy_delay = 1
        self.target_noise = 0.0
        self.noise_clip = 0.5
        self.exploration_noise = 0.1
        self.training_intensity = 1.0
        self.rollout_fragment_length = 1
        self.num_env_runners = 1
        self.model = {"hiddens": (256, 256), "activation": "relu"}

    def _training_keys(self):
        return {"replay_buffer_capacity", "tau", "twin_q", "policy_delay",
                "target_noise", "noise_clip", "exploration_noise",
                "num_steps_sampled_before_learning_starts",
                "training_intensity"}

    def learner_config_dict(self) -> Dict:
        d = super().learner_config_dict()
        d.update({"tau": self.tau, "twin_q": self.twin_q,
                  "policy_delay": self.policy_delay,
                  "target_noise": self.target_noise,
                  "noise_clip": self.noise_clip})
        return d

    def module_spec(self) -> DDPGModuleSpec:
        base = super().module_spec()
        if base.discrete:
            raise ValueError("DDPG/TD3 are continuous-control only")
        return DDPGModuleSpec(
            obs_dim=base.obs_dim, action_dim=base.action_dim,
            hiddens=tuple(self.model.get("hiddens", (256, 256))),
            activation=self.model.get("activation", "relu"),
            exploration_noise=self.exploration_noise)


class TD3Config(DDPGConfig):
    """Fujimoto 2018 defaults (reference: rllib td3.py)."""

    def __init__(self, algo_class=None):
        super().__init__(algo_class=algo_class or TD3)
        self.twin_q = True
        self.policy_delay = 2
        self.target_noise = 0.2


class DDPG(Algorithm):
    learner_cls = DDPGLearner

    @classmethod
    def get_default_config(cls):
        return DDPGConfig(algo_class=cls)

    def setup(self, _config) -> None:
        super().setup(_config)
        self.replay = ReplayBuffer(self.config.replay_buffer_capacity,
                                   seed=self.config.seed)

    def _make_runner(self, idx: int):
        cfg = self.config
        from ray_tpu.rllib.env.env_runner import SingleAgentEnvRunner

        return ray_tpu.remote(SingleAgentEnvRunner).options(
            resources={"CPU": 1}).remote(
                cfg.make_env(), cfg.num_envs_per_env_runner,
                cfg.rollout_fragment_length, self._module_spec,
                seed=cfg.seed + idx * 1000 + 1, explore=cfg.explore,
                gamma=cfg.gamma, collect_next_obs=True,
                connector=cfg.connector)

    def training_step(self) -> Dict:
        cfg = self.config
        learner = self.learner_group.local_learner()
        weights_ref = ray_tpu.put(learner.get_weights())

        samples = self._sample_from_runners(weights_ref)
        new_steps = sum(s["env_steps"] for s in samples)
        for s in samples:
            flat = lambda a: a.reshape((-1,) + a.shape[2:])
            mask = flat(s["valid"])
            self.replay.add_batch({
                "obs": flat(s["obs"])[mask],
                "actions": flat(s["actions"])[mask],
                "rewards": flat(s["rewards"])[mask],
                "next_obs": flat(s["next_obs"])[mask],
                "dones": flat(s["dones"])[mask],
            })

        metrics: Dict = {"env_steps_this_iter": new_steps}
        if len(self.replay) < cfg.num_steps_sampled_before_learning_starts:
            return metrics
        num_updates = max(1, int(new_steps * cfg.training_intensity /
                                 max(cfg.train_batch_size, 1)))
        for _ in range(num_updates):
            metrics.update(learner.update(
                self.replay.sample(cfg.train_batch_size)))
        return metrics


class TD3(DDPG):
    @classmethod
    def get_default_config(cls):
        return TD3Config(algo_class=cls)
