"""Named rendezvous/transport store actor for host-side collectives.

Reference analog: python/ray/util/collective/collective_group/gloo_util.py:29-98
(the named-actor Store used for gloo rendezvous). Here the store carries both
rendezvous *and* the cross-member payloads of the DCN fallback path: on a real
multi-host TPU pod, bulk traffic rides ICI inside the global XLA mesh and this
store only ever sees group metadata.

All methods are non-blocking so a ``max_concurrency=1`` actor can serve every
member; callers poll.
"""

from __future__ import annotations

import time
from typing import Any, Dict, List, Optional


class CollectiveStore:
    """One instance per group, named ``_collective_store:{group_name}``."""

    def __init__(self):
        # op_key -> {rank: payload}
        self._parts: Dict[str, Dict[int, Any]] = {}
        # op_key -> number of members that already read the completed set
        self._reads: Dict[str, int] = {}
        self._p2p: Dict[str, Any] = {}
        self._members: Dict[int, float] = {}

    def register(self, rank: int) -> int:
        self._members[rank] = time.time()
        return len(self._members)

    def num_members(self) -> int:
        return len(self._members)

    def deregister(self, rank: int) -> int:
        self._members.pop(rank, None)
        return len(self._members)

    def contribute(self, op_key: str, rank: int, payload: Any) -> int:
        parts = self._parts.setdefault(op_key, {})
        parts[rank] = payload
        return len(parts)

    def collect(self, op_key: str, world_size: int) -> Optional[List[Any]]:
        """Return payloads ordered by rank once all members contributed.

        The entry is garbage-collected after ``world_size`` successful reads.
        """
        parts = self._parts.get(op_key)
        if parts is None or len(parts) < world_size:
            return None
        out = [parts[r] for r in range(world_size)]
        reads = self._reads.get(op_key, 0) + 1
        if reads >= world_size:
            del self._parts[op_key]
            self._reads.pop(op_key, None)
        else:
            self._reads[op_key] = reads
        return out

    def put_p2p(self, key: str, payload: Any) -> None:
        self._p2p[key] = payload

    def take_p2p(self, key: str) -> Optional[List[Any]]:
        """Boxed result ([payload] or None) so None payloads round-trip."""
        if key in self._p2p:
            return [self._p2p.pop(key)]
        return None
