"""DataParallelTrainer (reference:
python/ray/train/data_parallel_trainer.py:25 — drives BackendExecutor over a
WorkerGroup; SURVEY §3.4 call stack)."""

from __future__ import annotations

import os
import time
from typing import Any, Callable, Dict, Optional

from ray_tpu.air.config import (
    CheckpointConfig, FailureConfig, RunConfig, ScalingConfig)
from ray_tpu.exceptions import (
    ActorDiedError, ActorUnavailableError, NodeDiedError, RayActorError,
    WorkerCrashedError)
from ray_tpu.train._checkpoint import Checkpoint
from ray_tpu.train._internal.backend_executor import (
    BackendExecutor, TrainingWorkerError)
from ray_tpu.train._internal.checkpoint_manager import CheckpointManager
from ray_tpu.train.base_trainer import (
    BaseTrainer, Result, TrainingFailedError)


class DataParallelTrainer(BaseTrainer):
    _backend_config_cls = None  # subclasses set (e.g. JaxConfig)

    def __init__(
        self,
        train_loop_per_worker: Callable[[Dict], None],
        *,
        train_loop_config: Optional[Dict] = None,
        backend_config=None,
        scaling_config: Optional[ScalingConfig] = None,
        run_config: Optional[RunConfig] = None,
        resume_from_checkpoint: Optional[Checkpoint] = None,
        datasets: Optional[Dict[str, Any]] = None,
        dataset_config=None,
    ):
        super().__init__(scaling_config=scaling_config, run_config=run_config,
                         resume_from_checkpoint=resume_from_checkpoint,
                         datasets=datasets)
        self.dataset_config = dataset_config
        self.train_loop_per_worker = train_loop_per_worker
        self.train_loop_config = train_loop_config or {}
        if backend_config is None:
            if self._backend_config_cls is None:
                raise ValueError("backend_config required")
            backend_config = self._backend_config_cls()
        self.backend_config = backend_config

    # Worker-group failures that warrant a full (slice-granular) restart:
    # the user loop raising is a TrainingWorkerError; an actor/host death
    # surfaces as a runtime actor error from ray_tpu.get.
    _RESTARTABLE = (TrainingWorkerError, RayActorError, ActorDiedError,
                    ActorUnavailableError, WorkerCrashedError, NodeDiedError)

    # ------------------------------------------------------------------ run
    def training_loop(self) -> Result:
        failure_config = self.run_config.failure_config or FailureConfig()
        ckpt_manager = CheckpointManager(self.run_config.checkpoint_config)
        latest_metrics: Optional[Dict] = None
        checkpoint_path: Optional[str] = (
            self.resume_from_checkpoint.path
            if self.resume_from_checkpoint else None)
        failures = 0
        error: Optional[Exception] = None
        pg = self._reserve_placement_group()
        try:
            return self._run_with_pg(
                pg, failure_config, ckpt_manager, latest_metrics,
                checkpoint_path, failures, error)
        finally:
            self._release_placement_group(pg)

    def _run_with_pg(self, pg, failure_config, ckpt_manager, latest_metrics,
                     checkpoint_path, failures, error) -> Result:
        while True:
            executor = BackendExecutor(
                self.backend_config,
                self.scaling_config.num_workers,
                self.scaling_config._resources(),
                placement_group=pg,
            )
            try:
                executor.start()
                executor.start_training(
                    self.train_loop_per_worker,
                    self.train_loop_config,
                    experiment_name=self._experiment_name,
                    storage_path=self._storage_path,
                    trial_dir=self._trial_dir,
                    checkpoint_path=checkpoint_path,
                    dataset_shards=self._split_datasets(),
                )
                while True:
                    results = executor.get_next_results()
                    if results is None:
                        break
                    # rank-0's metrics are canonical (reference consolidates
                    # the same way in _fetch_next_result); fall back to the
                    # lowest live rank once rank 0 finishes early
                    by_rank = {r.world_rank: r for r in results
                               if getattr(r, "world_rank", None) is not None}
                    canonical = (by_rank[min(by_rank)] if by_rank
                                 else results[0])
                    latest_metrics = canonical.metrics
                    ckpt_dirs = [r.checkpoint_dir for r in results
                                 if r.checkpoint_dir]
                    report_fn = getattr(self, "_tune_report_fn", None)
                    if report_fn is not None:
                        # stream per-iteration results to Tune (reference
                        # wires this through the shared Train/Tune session)
                        report_fn(latest_metrics,
                                  ckpt_dirs[0] if ckpt_dirs else None)
                    if ckpt_dirs:
                        checkpoint_path = ckpt_dirs[0]
                        ckpt_manager.register_checkpoint(
                            Checkpoint(checkpoint_path), latest_metrics or {})
                        # pruning may have deleted a badly-scoring newest
                        # checkpoint; restart from one that still exists
                        latest = ckpt_manager.latest_checkpoint
                        if latest is not None:
                            checkpoint_path = latest.path
                error = None
                break
            except self._RESTARTABLE as e:
                failures += 1
                error = TrainingFailedError(str(e))
                if failure_config.fail_fast or \
                        failures > failure_config.max_failures >= 0:
                    break
                # Slice-granular restart: tear the whole group down and
                # relaunch from the latest checkpoint (SURVEY §7 hard part 4).
            finally:
                executor.shutdown()

        return Result(
            metrics=latest_metrics,
            checkpoint=ckpt_manager.latest_checkpoint or (
                Checkpoint(checkpoint_path) if checkpoint_path else None),
            path=self._trial_dir,
            error=error,
            best_checkpoints=ckpt_manager.best_checkpoints(),
        )

    # ------------------------------------------------------ placement group
    def _reserve_placement_group(self):
        """Gang-reserve one bundle per worker with the ScalingConfig strategy
        (reference: Tune's placement-group-per-trial,
        tune/execution/placement_groups.py; a slice is one gang)."""
        from ray_tpu.util.placement_group import placement_group

        pg = placement_group(
            self.scaling_config.as_placement_group_bundles(),
            strategy=self.scaling_config.placement_strategy,
        )
        if not pg.wait(timeout_seconds=120):
            from ray_tpu.util.placement_group import remove_placement_group

            remove_placement_group(pg)
            raise TrainingFailedError(
                "could not reserve training resources: placement group "
                f"{self.scaling_config.as_placement_group_bundles()} "
                "not placeable within 120s")
        return pg

    def _release_placement_group(self, pg) -> None:
        from ray_tpu.util.placement_group import remove_placement_group

        try:
            remove_placement_group(pg)
        except Exception:
            pass

    # ------------------------------------------------------------- datasets
    def _split_datasets(self):
        """Per-worker dataset shards via DataConfig (reference:
        train/_internal/data_config.py — train dataset split, others
        replicated)."""
        from ray_tpu.train._internal.data_config import DataConfig

        cfg = getattr(self, "dataset_config", None) or DataConfig()
        return cfg.configure(self.datasets, self.scaling_config.num_workers)
