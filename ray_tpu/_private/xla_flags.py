"""XLA_FLAGS hygiene shared by the test conftest and the bench harness.

jaxlib hard-aborts the whole process (``parse_flags_from_env.cc`` FATAL
"Unknown flags in XLA_FLAGS") the first time a backend initializes if
``XLA_FLAGS`` names a flag the build doesn't know. Tuning flags that were
valid for one jaxlib (collective rendezvous deadlines, eigen threading)
silently become process-killers after a toolchain bump — observed as a
SIGABRT mid-test-suite at the first driver-side jax computation.

``supported_xla_flags`` probes the CURRENT jaxlib in a scratch subprocess
and drops exactly the flags it rejects. The verdict is cached in /tmp
keyed by jaxlib version + flag set, so the ~seconds-long probe runs once
per toolchain, not once per pytest invocation.
"""

from __future__ import annotations

import hashlib
import json
import os
import re
import subprocess
import sys
import tempfile
from typing import List, Optional

_PROBE_SRC = (
    "import jax\n"
    "jax.config.update('jax_platforms', 'cpu')\n"
    "jax.devices()\n"
)


def _jaxlib_version() -> str:
    try:
        import jaxlib.version

        return jaxlib.version.__version__
    except Exception:
        return "unknown"


def _cache_path(flags: List[str]) -> str:
    key = hashlib.sha256(
        (" ".join(flags) + "::" + _jaxlib_version()).encode()).hexdigest()[:16]
    return os.path.join(tempfile.gettempdir(),
                        f"ray_tpu_xla_flag_probe_{key}.json")


def _probe_once(flags: List[str], timeout_s: float):
    """One backend-init probe run; returns the CompletedProcess or None
    when the probe itself couldn't run."""
    env = dict(os.environ)
    env["XLA_FLAGS"] = " ".join(flags)
    env["JAX_PLATFORMS"] = "cpu"
    try:
        from ray_tpu._private.config import scrub_axon_bootstrap_env

        scrub_axon_bootstrap_env(env)
    except Exception:
        pass
    try:
        return subprocess.run([sys.executable, "-c", _PROBE_SRC], env=env,
                              capture_output=True, text=True,
                              timeout=timeout_s)
    except Exception:
        return None


def _probe(flags: List[str], timeout_s: float) -> Optional[List[str]]:
    """Iteratively probe until a subset of ``flags`` passes backend init.

    Every candidate that gets CACHED has itself survived a probe — a
    filtered set can fail in a NEW way (dropping all ``--`` flags leaves
    a bare token leading, which XLA treats as a flags-file name and
    FATALs on), and caching such a set would crash every later run.
    Returns None when no verdict could be produced (keep flags as-is)."""
    cur = list(flags)
    for _ in range(4):
        if not cur:
            return cur
        r = _probe_once(cur, timeout_s)
        if r is None:
            return None
        if r.returncode == 0:
            return cur
        m = re.search(r"Unknown flags in XLA_FLAGS:([^\n]*)",
                      r.stderr + r.stdout)
        if m:
            unknown = set(m.group(1).split())
            nxt = [f for f in cur if f not in unknown]
        else:
            # fatal without a flag attribution (e.g. leading bare token
            # misread as a flags file): shed bare tokens, then give up
            nxt = [f for f in cur if f.startswith("--")]
        if nxt == cur:
            return []  # no progress: no tuning flags beats an abort
        cur = nxt
    return []


def normalize_xla_flags(value: str) -> str:
    """Order ``--``-prefixed flags before bare tokens: XLA treats a
    LEADING non-``--`` token as the name of a flags file and FATALs when
    it can't open it (parse_flags_from_env.cc:169). A leading token that
    IS an existing file is the documented flags-file form — leave the
    value untouched so we don't break it."""
    toks = value.split()
    if toks and not toks[0].startswith("--") and os.path.exists(toks[0]):
        return value
    return " ".join(sorted(toks, key=lambda t: not t.startswith("--")))


def supported_xla_flags(flags: List[str],
                        timeout_s: float = 120.0) -> List[str]:
    """Filter ``flags`` down to what the current jaxlib accepts."""
    flags = [f for f in flags if f]
    if not flags:
        return flags
    cache = _cache_path(flags)
    try:
        with open(cache) as f:
            kept = json.load(f)
        if isinstance(kept, list):
            return kept
    except (OSError, ValueError):
        pass
    kept = _probe(flags, timeout_s)
    if kept is None:
        return flags
    try:
        tmp = cache + f".tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(kept, f)
        os.replace(tmp, cache)
    except OSError:
        pass
    return kept
