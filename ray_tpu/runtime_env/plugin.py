"""Runtime-env plugin interface (reference:
python/ray/_private/runtime_env/plugin.py:24 RuntimeEnvPlugin ABC).

Built-in fields (env_vars / working_dir / py_modules / pip / conda) are
implemented as plugins too, so third-party fields register the same way.
"""

from __future__ import annotations

import hashlib
import importlib
import os
import shutil
import sys
from typing import Any, Dict, Optional

from ray_tpu.runtime_env.runtime_env import RuntimeEnvSetupError


class RuntimeEnvPlugin:
    """Setup hook for one runtime_env field."""

    name: str = ""
    priority: int = 10  # lower runs earlier

    def validate(self, value: Any) -> None:
        pass

    def setup(self, value: Any, context: "RuntimeEnvContext") -> None:
        """Apply the field inside the worker process."""
        raise NotImplementedError


_PLUGINS: Dict[str, RuntimeEnvPlugin] = {}


def register_plugin(plugin: RuntimeEnvPlugin) -> None:
    _PLUGINS[plugin.name] = plugin


def get_plugin(name: str) -> Optional[RuntimeEnvPlugin]:
    return _PLUGINS.get(name)


# ---------------------------------------------------------------- built-ins

def _excluded(rel: str, excludes) -> bool:
    """gitignore-flavored match on slash-normalized relative paths: a
    pattern excludes exact matches, fnmatch matches, and everything under a
    matched directory."""
    import fnmatch

    rel = rel.replace(os.sep, "/")
    for pat in excludes or ():
        pat = pat.rstrip("/")
        if (rel == pat or fnmatch.fnmatch(rel, pat)
                or rel.startswith(pat + "/")
                or fnmatch.fnmatch(rel, pat + "/*")):
            return True
    return False


def _stage_dir(src: str, cache_root: str, excludes=None) -> str:
    """Copy ``src`` into a content-addressed cache dir (the URI-cache analog,
    reference: _private/runtime_env/uri_cache.py); reuses an existing copy.
    Hash and copy use the SAME exclude predicate — a mismatch would produce
    stale cache hits."""
    h = hashlib.sha256()
    kept = []
    for root, dirs, files in os.walk(src):
        dirs.sort()
        reldir = os.path.relpath(root, src)
        dirs[:] = [d for d in dirs if not _excluded(
            os.path.normpath(os.path.join(reldir, d)), excludes)]
        for fname in sorted(files):
            path = os.path.join(root, fname)
            rel = os.path.normpath(os.path.join(reldir, fname))
            if _excluded(rel, excludes):
                continue
            h.update(rel.encode())
            st = os.stat(path)
            h.update(f"{st.st_size}:{int(st.st_mtime)}".encode())
            kept.append((path, rel))
    digest = h.hexdigest()[:16]
    dest = os.path.join(cache_root, f"working_dir_{digest}")
    if not os.path.isdir(dest):
        tmp = dest + f".tmp{os.getpid()}"
        for path, rel in kept:
            target = os.path.join(tmp, rel)
            os.makedirs(os.path.dirname(target), exist_ok=True)
            shutil.copy2(path, target)
        os.makedirs(tmp, exist_ok=True)  # empty src edge case
        try:
            os.rename(tmp, dest)
        except OSError:
            shutil.rmtree(tmp, ignore_errors=True)  # lost a race: reuse dest
    return dest


class EnvVarsPlugin(RuntimeEnvPlugin):
    name = "env_vars"
    priority = 0

    def setup(self, value: Dict[str, str], context) -> None:
        os.environ.update(value)


class WorkingDirPlugin(RuntimeEnvPlugin):
    name = "working_dir"
    priority = 1

    def setup(self, value: str, context) -> None:
        if value.startswith(("http://", "https://", "gs://", "s3://")):
            raise RuntimeEnvSetupError(
                "remote working_dir URIs need network access, which this "
                "deployment forbids; use a local path")
        staged = _stage_dir(value, context.cache_root,
                            context.spec.get("excludes"))
        os.chdir(staged)
        if staged not in sys.path:
            sys.path.insert(0, staged)


class PyModulesPlugin(RuntimeEnvPlugin):
    name = "py_modules"
    priority = 2

    def setup(self, value, context) -> None:
        for mod in value:
            path = os.path.abspath(mod)
            if path.endswith(".py"):
                path = os.path.dirname(path)
            if path not in sys.path:
                sys.path.insert(0, path)


class PipCheckPlugin(RuntimeEnvPlugin):
    """No-install policy: verify the requested packages are already
    importable instead of calling pip (reference behavior installs via
    _private/runtime_env/pip.py; this image forbids installs)."""

    name = "pip"
    priority = 3

    def setup(self, value, context) -> None:
        if isinstance(value, dict):
            value = value.get("packages", [])
        if isinstance(value, str):
            raise RuntimeEnvSetupError(
                "pip requirements files are not supported in the no-install "
                "deployment; list packages explicitly")
        import importlib.metadata as im

        missing = []
        for req in value:
            dist = (req.split("==")[0].split(">=")[0].split("<=")[0]
                    .split("[")[0].strip())
            try:
                im.version(dist)  # distribution name (handles scikit-learn)
                continue
            except im.PackageNotFoundError:
                pass
            try:  # fall back: module name given directly (e.g. "sklearn")
                importlib.import_module(dist.replace("-", "_"))
            except ImportError:
                missing.append(req)
        if missing:
            raise RuntimeEnvSetupError(
                f"packages {missing} are not pre-installed and this "
                "deployment forbids network installs")


class CondaGatePlugin(RuntimeEnvPlugin):
    name = "conda"
    priority = 3

    def setup(self, value, context) -> None:
        raise RuntimeEnvSetupError(
            "conda environments are not supported in the no-install "
            "deployment")


for _p in (EnvVarsPlugin(), WorkingDirPlugin(), PyModulesPlugin(),
           PipCheckPlugin(), CondaGatePlugin()):
    register_plugin(_p)
