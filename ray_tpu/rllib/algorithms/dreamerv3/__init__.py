from ray_tpu.rllib.algorithms.dreamerv3.dreamerv3 import (
    DreamerV3, DreamerV3Config)

__all__ = ["DreamerV3", "DreamerV3Config"]
