"""Conda runtime-env: workers start under a conda environment's python
(VERDICT r2 missing #3; reference: python/ray/_private/runtime_env/conda.py
— which materializes the env with ``conda env create`` keyed by a spec
hash, then rewrites the worker command's interpreter to the env's python).

Like ``container``, conda is a SPAWN-TIME field: a running worker cannot
swap its interpreter, so the agent launches a fresh worker process with
``<prefix>/bin/python`` and tags it with the runtime_env hash so pool
affinity never mixes it with host workers
(``agent._pop_idle_worker(tagged_only=True)``).

Spec shapes (reference parity):
    {"conda": "env-name-or-prefix-path"}        # use an existing env
    {"conda": {"dependencies": ["python=3.11", {"pip": ["x"]}],
               "channels": ["conda-forge"]}}    # materialize from a spec

Everything that can be checked without a conda install is a pure function
(command shape, digest, YAML emission, prefix resolution against a fake
env tree) — the same offline-test pattern as the GKE REST client and the
container command builder. Env *creation* needs a conda binary and, in
this zero-egress image, an offline package cache; both are surfaced as
RuntimeEnvSetupError, not crashes.
"""

from __future__ import annotations

import hashlib
import json
import os
import shutil
import subprocess
import sys
from typing import Any, Dict, List, Optional, Tuple

from ray_tpu.runtime_env.plugin import RuntimeEnvPlugin, register_plugin
from ray_tpu.runtime_env.runtime_env import RuntimeEnvSetupError


def conda_binary() -> Optional[str]:
    """Resolve the fastest available conda-compatible solver binary."""
    for name in ("mamba", "conda", "micromamba"):
        path = shutil.which(name)
        if path:
            return path
    return None


def validate_conda_spec(spec: Any) -> None:
    if isinstance(spec, str):
        if not spec:
            raise ValueError("conda env name must be non-empty")
        return
    if isinstance(spec, dict):
        deps = spec.get("dependencies")
        if not isinstance(deps, list) or not deps:
            raise ValueError(
                'conda dict spec needs a non-empty "dependencies" list '
                "(environment.yml schema)")
        for d in deps:
            if not isinstance(d, (str, dict)):
                raise TypeError(
                    f"conda dependency entries must be str or "
                    f"{{'pip': [...]}}; got {d!r}")
        return
    raise TypeError(
        f"conda runtime_env must be an env name/prefix or an "
        f"environment.yml dict; got {type(spec).__name__}")


def spec_digest(spec: Dict) -> str:
    """Content hash of a dict spec — the env cache key (the reference keys
    on the hash of the serialized conda config the same way)."""
    return hashlib.sha256(
        json.dumps(spec, sort_keys=True).encode()).hexdigest()[:16]


def emit_environment_yaml(spec: Dict) -> str:
    """Serialize a dict spec to environment.yml text.

    Hand-rolled because the schema is tiny (name/channels/dependencies
    with one optional nested ``{"pip": [...]}`` map) and the image may not
    ship pyyaml; values are JSON-quoted, which is valid YAML.
    """
    lines: List[str] = []
    if spec.get("name"):
        lines.append(f"name: {json.dumps(str(spec['name']))}")
    for key in ("channels",):
        if spec.get(key):
            lines.append(f"{key}:")
            lines += [f"  - {json.dumps(str(c))}" for c in spec[key]]
    lines.append("dependencies:")
    for dep in spec.get("dependencies", []):
        if isinstance(dep, str):
            lines.append(f"  - {json.dumps(dep)}")
        else:  # {"pip": [...]}
            for sub_key, sub_list in dep.items():
                lines.append(f"  - {json.dumps(str(sub_key))}:")
                lines += [f"    - {json.dumps(str(p))}" for p in sub_list]
    return "\n".join(lines) + "\n"


def create_env_command(binary: str, prefix: str,
                       yaml_path: str) -> List[str]:
    """argv that materializes ``yaml_path`` into ``prefix``. micromamba
    dropped the ``env`` subcommand alias; conda/mamba share it."""
    base = os.path.basename(binary)
    if base == "micromamba":
        return [binary, "create", "--yes", "-p", prefix, "-f", yaml_path]
    return [binary, "env", "create", "-p", prefix, "-f", yaml_path]


def env_python(prefix: str) -> str:
    return os.path.join(prefix, "bin", "python")


def _candidate_roots() -> List[str]:
    roots = []
    for env_var in ("CONDA_ENVS_PATH", "CONDA_ENVS_DIRS"):
        val = os.environ.get(env_var)
        if val:
            roots += val.split(os.pathsep)
    conda_prefix = os.environ.get("CONDA_PREFIX")
    if conda_prefix:
        # activated env: envs live next to the base install
        roots.append(os.path.join(conda_prefix, "envs"))
        roots.append(os.path.join(os.path.dirname(
            os.path.dirname(conda_prefix)), "envs"))
    home = os.path.expanduser("~")
    for base in ("miniconda3", "anaconda3", "miniforge3", "mambaforge",
                 ".conda"):
        roots.append(os.path.join(home, base, "envs"))
    return roots


def resolve_env_prefix(name_or_path: str,
                       binary: Optional[str] = None) -> str:
    """Map an env name or prefix path to a concrete prefix containing
    ``bin/python``. Raises RuntimeEnvSetupError when nothing matches."""
    if os.sep in name_or_path or name_or_path.startswith("~"):
        prefix = os.path.expanduser(name_or_path)
        if os.path.exists(env_python(prefix)):
            return prefix
        raise RuntimeEnvSetupError(
            f"conda prefix {prefix} has no bin/python")
    for root in _candidate_roots():
        prefix = os.path.join(root, name_or_path)
        if os.path.exists(env_python(prefix)):
            return prefix
    if binary:
        try:
            out = subprocess.run(
                [binary, "env", "list", "--json"], capture_output=True,
                text=True, timeout=60)
            for prefix in json.loads(out.stdout or "{}").get("envs", []):
                if os.path.basename(prefix) == name_or_path and \
                        os.path.exists(env_python(prefix)):
                    return prefix
        except Exception:
            pass
    raise RuntimeEnvSetupError(
        f"conda env {name_or_path!r} not found (no matching prefix under "
        f"known env roots{' and conda env list came up empty' if binary else ', and no conda binary is installed to query'})")


def ensure_conda_env(spec: Any, cache_root: str,
                     binary: Optional[str] = None) -> str:
    """Resolve (and for dict specs, materialize-on-miss) the env prefix.

    Dict specs are content-addressed under ``<cache_root>/conda_envs`` and
    creation is serialized with an flock, mirroring the pip plugin's
    venv cache discipline.
    """
    binary = binary or conda_binary()
    if isinstance(spec, str):
        return resolve_env_prefix(spec, binary)
    envs_root = os.path.join(cache_root, "conda_envs")
    os.makedirs(envs_root, exist_ok=True)
    prefix = os.path.join(envs_root, spec_digest(spec))
    if os.path.exists(env_python(prefix)):
        return prefix
    if binary is None:
        raise RuntimeEnvSetupError(
            "conda runtime_env requested but no conda/mamba/micromamba "
            "binary is installed on this node")
    import fcntl

    lock_path = prefix + ".lock"
    with open(lock_path, "w") as lock:
        fcntl.flock(lock, fcntl.LOCK_EX)
        try:
            if os.path.exists(env_python(prefix)):
                return prefix
            yaml_path = prefix + ".yml"
            with open(yaml_path, "w") as f:
                f.write(emit_environment_yaml(spec))
            r = subprocess.run(
                create_env_command(binary, prefix, yaml_path),
                capture_output=True, text=True, timeout=1800)
            if r.returncode != 0 or not os.path.exists(env_python(prefix)):
                shutil.rmtree(prefix, ignore_errors=True)
                raise RuntimeEnvSetupError(
                    f"conda env create failed:\n{r.stdout}\n{r.stderr}\n"
                    "(note: this deployment has no network egress — the "
                    "env must resolve from a local package cache)")
        finally:
            fcntl.flock(lock, fcntl.LOCK_UN)
    return prefix


def worker_conda_command(prefix: str, env: Dict[str, str]
                         ) -> Tuple[List[str], Dict[str, str]]:
    """(argv, env-overrides) launching this framework's worker process
    under the env's interpreter. The ray_tpu package parent rides
    PYTHONPATH because the env will not have the framework installed —
    the same trick the container plugin uses with a bind-mount."""
    import ray_tpu

    pkg_parent = os.path.dirname(os.path.dirname(
        os.path.abspath(ray_tpu.__file__)))
    overrides = dict(env)
    tail = overrides.get("PYTHONPATH", os.environ.get("PYTHONPATH", ""))
    # no trailing separator when there is no tail: an empty PYTHONPATH
    # component means cwd, which would let staged working_dir files
    # shadow stdlib modules inside the env interpreter
    overrides["PYTHONPATH"] = (pkg_parent + os.pathsep + tail) if tail \
        else pkg_parent
    overrides["PATH"] = os.path.join(prefix, "bin") + os.pathsep + \
        os.environ.get("PATH", "")
    overrides["CONDA_PREFIX"] = prefix
    overrides["CONDA_DEFAULT_ENV"] = os.path.basename(prefix)
    cmd = [env_python(prefix), "-m", "ray_tpu._private.worker_process"]
    return cmd, overrides


class CondaPlugin(RuntimeEnvPlugin):
    """Validation + spawn-time marker; by the time the worker runs it is
    already the conda env's interpreter (agent launched it that way)."""

    name = "conda"
    priority = 0
    spawn_time = True

    def validate(self, value) -> None:
        validate_conda_spec(value)

    def setup(self, value, context) -> None:
        pass


register_plugin(CondaPlugin())
