"""Container runtime-env: workers start inside podman/docker
(VERDICT r2 item 7; reference: python/ray/_private/runtime_env/container.py
— the reference prepends ``podman run`` to the worker command with the
session dir and the ray package bind-mounted; same design here).

Unlike every other runtime_env field, a container cannot be applied
in-process: the AGENT wraps the worker launch command at spawn time
(``agent._spawn_worker(container=...)``); the plugin below only
validates and marks the field as spawn-time so the worker-side
``setup_runtime_env`` skips it. Container workers are spawned pre-tagged
with the runtime_env's hash, so worker-pool affinity
(``agent._pop_idle_worker``) never hands a containerized lease a host
worker or vice versa.

Spec shape (reference parity: container.py ``worker_path``/``run_options``):
    {"container": {"image": "img:tag",
                   "engine": "podman"|"docker",   # optional, auto-detect
                   "run_options": ["--cap-drop", "ALL"],  # optional
                   "pull": true}}                  # optional eager pull
"""

from __future__ import annotations

import os
import shutil
import sys
from typing import Dict, List, Optional

from ray_tpu.runtime_env.plugin import RuntimeEnvPlugin, register_plugin
from ray_tpu.runtime_env.runtime_env import RuntimeEnvSetupError


def container_engine(spec: Dict) -> Optional[str]:
    """Resolve the container engine binary, or None if none installed."""
    explicit = spec.get("engine")
    if explicit:
        return shutil.which(explicit)
    for engine in ("podman", "docker"):
        path = shutil.which(engine)
        if path:
            return path
    return None


def validate_container_spec(spec) -> None:
    if not isinstance(spec, dict) or not spec.get("image"):
        raise ValueError(
            'container runtime_env must be {"image": "...", ...}; got '
            f"{spec!r}")
    ro = spec.get("run_options", [])
    if not isinstance(ro, (list, tuple)) or not all(
            isinstance(o, str) for o in ro):
        raise TypeError("container.run_options must be a list of strings")


def build_container_command(spec: Dict, inner_cmd: List[str],
                            mounts: List[str], env: Dict[str, str],
                            engine: str = "docker") -> List[str]:
    """The full ``docker run`` argv wrapping a worker launch. Split out as
    a pure function so the command shape is unit-testable without any
    container engine installed (the same offline pattern as the GKE REST
    client's payload builder)."""
    cmd = [engine, "run", "--rm",
           # the worker dials the agent's unix socket + TCP ports and
           # binds its own direct-call port the driver must reach
           "--network=host", "--ipc=host"]
    seen = set()
    for mount in mounts:
        if mount and mount not in seen:
            seen.add(mount)
            cmd += ["-v", f"{mount}:{mount}"]
    for key, value in sorted(env.items()):
        cmd += ["-e", f"{key}={value}"]
    cmd += list(spec.get("run_options", []))
    cmd.append(spec["image"])
    cmd += inner_cmd
    return cmd


def worker_container_command(spec: Dict, session_dir: str, store_dir: str,
                             env: Dict[str, str],
                             engine: Optional[str] = None) -> List[str]:
    """Concrete wrap for this framework's worker process: bind-mounts the
    session dir (unix socket + logs), the object-store dir (shm-backed
    blocks), and the ray_tpu package itself (the image need not have the
    framework installed — reference container.py mounts the ray wheel the
    same way)."""
    engine = engine or container_engine(spec)
    if engine is None:
        raise RuntimeEnvSetupError(
            "container runtime_env requested but neither podman nor "
            "docker is installed on this node")
    import ray_tpu

    pkg_parent = os.path.dirname(os.path.dirname(
        os.path.abspath(ray_tpu.__file__)))
    env = dict(env)
    env["PYTHONPATH"] = pkg_parent + os.pathsep + env.get("PYTHONPATH", "")
    mounts = [session_dir, store_dir, pkg_parent]
    inner = ["python", "-m", "ray_tpu._private.worker_process"]
    return build_container_command(spec, inner, mounts, env, engine=engine)


class ContainerPlugin(RuntimeEnvPlugin):
    """Validation + spawn-time marker. ``setup`` is a no-op by design: by
    the time the worker process runs, it is already inside the container
    (the agent wrapped the launch command)."""

    name = "container"
    priority = 0
    spawn_time = True  # consumed by the agent, not the worker

    def validate(self, value) -> None:
        validate_container_spec(value)

    def setup(self, value, context) -> None:
        pass


register_plugin(ContainerPlugin())
