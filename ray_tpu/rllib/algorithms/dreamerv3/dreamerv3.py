"""DreamerV3 — model-based RL via latent imagination (reference:
rllib/algorithms/dreamerv3/dreamerv3.py (TF); Hafner 2023): an RSSM world
model (GRU deterministic path + categorical stochastic latents) learns to
predict observations, rewards, and episode continuation; the actor-critic
trains entirely on imagined latent rollouts, so the env is touched only
for replay data.

JAX-native and compact, keeping the paper's robustness machinery:
symlog targets, twohot reward/value distributions over symexp-spaced
bins, unimix categorical latents with straight-through gradients, KL
balancing with free bits, percentile return normalization for the actor,
and an EMA critic regularizer. Deviations (documented, sized for the
1-CPU test box): MLP encoder/decoder only (no CNN path), discrete
actions only, and imagination starts from every posterior state of the
replayed batch.

Everything trains under one jit: the RSSM scan over the sequence and the
imagination scan over the horizon are both ``lax.scan``s, which is the
TPU-shaped way to run this (static shapes, no per-step Python).
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax

import ray_tpu
from ray_tpu.rllib.algorithms.algorithm import Algorithm
from ray_tpu.rllib.algorithms.algorithm_config import AlgorithmConfig
from ray_tpu.rllib.utils.replay_buffer import SequenceReplayBuffer


# ----------------------------------------------------------- symlog/twohot
def symlog(x):
    return jnp.sign(x) * jnp.log1p(jnp.abs(x))


def symexp(x):
    return jnp.sign(x) * (jnp.exp(jnp.abs(x)) - 1.0)


def make_bins(num_bins: int = 41, low: float = -20.0, high: float = 20.0):
    """symexp-spaced bins: dense near 0, exponentially wide at the tails
    (Hafner 2023 uses 255 over [-20, 20]; fewer suffice at toy scale)."""
    return symexp(jnp.linspace(low, high, num_bins))


def twohot(x, bins):
    """Project scalars onto the two neighboring bins (linear weights)."""
    x = jnp.clip(x, bins[0], bins[-1])
    idx_hi = jnp.clip(jnp.searchsorted(bins, x), 1, len(bins) - 1)
    idx_lo = idx_hi - 1
    lo, hi = bins[idx_lo], bins[idx_hi]
    w_hi = jnp.where(hi > lo, (x - lo) / (hi - lo + 1e-12), 1.0)
    one_lo = jax.nn.one_hot(idx_lo, len(bins))
    one_hi = jax.nn.one_hot(idx_hi, len(bins))
    return one_lo * (1 - w_hi)[..., None] + one_hi * w_hi[..., None]


def dist_mean(logits, bins):
    return jnp.sum(jax.nn.softmax(logits) * bins, axis=-1)


# ------------------------------------------------------------------- module
@dataclasses.dataclass
class DreamerModuleSpec:
    obs_dim: int
    action_dim: int
    discrete: bool = True
    deter: int = 128          # GRU state size
    stoch: int = 8            # categorical latents
    classes: int = 8          # classes per latent
    hidden: int = 128         # MLP width for all heads
    num_bins: int = 41
    unimix: float = 0.01

    def build(self) -> "DreamerModule":
        return DreamerModule(self)


class DreamerModule:
    """RSSM + heads. Params are plain dicts of w/b MLP stacks (house
    style); the GRU is a single fused cell."""

    def __init__(self, spec: DreamerModuleSpec):
        self.spec = spec
        self.bins = make_bins(spec.num_bins)

    # --- param init -------------------------------------------------------
    def _mlp(self, key, sizes):
        layers = []
        for a, b in zip(sizes[:-1], sizes[1:]):
            key, sub = jax.random.split(key)
            layers.append({
                "w": jax.random.normal(sub, (a, b)) * jnp.sqrt(2.0 / a),
                "b": jnp.zeros((b,)),
            })
        return layers

    def init(self, rng) -> Dict:
        s = self.spec
        z_dim = s.stoch * s.classes
        gru_in = z_dim + s.action_dim
        keys = jax.random.split(rng, 9)
        h = s.hidden
        return {
            "embed": self._mlp(keys[0], (s.obs_dim, h, h)),
            # fused GRU: [x, h] -> (reset, update, candidate)
            "gru": {"w": jax.random.normal(
                keys[1], (gru_in + s.deter, 3 * s.deter))
                * jnp.sqrt(1.0 / (gru_in + s.deter)),
                "b": jnp.zeros((3 * s.deter,))},
            "prior": self._mlp(keys[2], (s.deter, h, z_dim)),
            "post": self._mlp(keys[3], (s.deter + h, h, z_dim)),
            "decoder": self._mlp(keys[4], (s.deter + z_dim, h, s.obs_dim)),
            "reward": self._mlp(keys[5], (s.deter + z_dim, h, s.num_bins)),
            "cont": self._mlp(keys[6], (s.deter + z_dim, h, 1)),
            "actor": self._mlp(keys[7], (s.deter + z_dim, h,
                                         s.action_dim)),
            "critic": self._mlp(keys[8], (s.deter + z_dim, h, s.num_bins)),
        }

    # --- building blocks --------------------------------------------------
    @staticmethod
    def _tower(layers, x, act=jax.nn.silu):
        for layer in layers[:-1]:
            x = act(x @ layer["w"] + layer["b"])
        last = layers[-1]
        return x @ last["w"] + last["b"]

    def _z_logits(self, raw):
        """(.., stoch*classes) -> unimix logits (.., stoch, classes)."""
        s = self.spec
        logits = raw.reshape(raw.shape[:-1] + (s.stoch, s.classes))
        probs = (1 - s.unimix) * jax.nn.softmax(logits) + \
            s.unimix / s.classes
        return jnp.log(probs)

    def _z_sample(self, logits, rng):
        """Straight-through categorical sample, flattened."""
        s = self.spec
        idx = jax.random.categorical(rng, logits)
        one = jax.nn.one_hot(idx, s.classes)
        probs = jax.nn.softmax(logits)
        one = one + probs - jax.lax.stop_gradient(probs)
        return one.reshape(one.shape[:-2] + (s.stoch * s.classes,))

    def sequence_step(self, params, h, z, action_onehot):
        """h_t = GRU(h_{t-1}, [z_{t-1}, a_{t-1}])."""
        x = jnp.concatenate([z, action_onehot], -1)
        return self._gru_cell(params, x, h)

    def _gru_cell(self, params, x, h):
        gates = jnp.concatenate([x, h], -1) @ params["gru"]["w"] + \
            params["gru"]["b"]
        r, u, c = jnp.split(gates, 3, axis=-1)
        r, u = jax.nn.sigmoid(r), jax.nn.sigmoid(u)
        return u * h + (1 - u) * jnp.tanh(r * c)

    def prior_logits(self, params, h):
        return self._z_logits(self._tower(params["prior"], h))

    def post_logits(self, params, h, obs):
        embed = self._tower(params["embed"], symlog(obs))
        return self._z_logits(self._tower(
            params["post"], jnp.concatenate([h, embed], -1)))

    def feat(self, h, z):
        return jnp.concatenate([h, z], -1)

    # --- env-runner interface (recurrent policy) -------------------------
    def initial_state(self, batch_size: int) -> Tuple:
        s = self.spec
        return (np.zeros((batch_size, s.deter), np.float32),
                np.zeros((batch_size, s.stoch * s.classes), np.float32),
                np.zeros((batch_size, s.action_dim), np.float32))

    def explore_action_recurrent(self, params, obs, state, rng):
        h, z, prev_a = state
        k1, k2 = jax.random.split(rng)
        h = self.sequence_step(params, h, z, prev_a)
        z = self._z_sample(self.post_logits(params, h, obs), k1)
        feat = self.feat(h, z)
        logits = self._tower(params["actor"], feat)
        action = jax.random.categorical(k2, logits)
        logp = jax.nn.log_softmax(logits)[
            jnp.arange(action.shape[0]), action]
        vf = dist_mean(self._tower(params["critic"], feat), self.bins)
        onehot = jax.nn.one_hot(action, self.spec.action_dim)
        return action, logp, vf, (h, z, onehot)

    def explore_action(self, params, obs, rng):
        """Stateless variant (the runner jits it unconditionally even for
        recurrent modules; R2D2 ships one the same way): one posterior
        step from a zero latent state."""
        action, logp, vf, _ = self.explore_action_recurrent(
            params, obs, self._zero_state(obs.shape[0]), rng)
        return action, logp, vf

    def _zero_state(self, batch_size: int) -> Tuple:
        s = self.spec
        return (jnp.zeros((batch_size, s.deter)),
                jnp.zeros((batch_size, s.stoch * s.classes)),
                jnp.zeros((batch_size, s.action_dim)))

    def forward(self, params, obs) -> Dict[str, jnp.ndarray]:
        """Stateless fallback (bootstrap values at truncations): runs one
        posterior step from a zero state."""
        B = obs.shape[0]
        s = self.spec
        h = jnp.zeros((B, s.deter))
        z_logits = self.post_logits(params, h, obs)
        z = jax.nn.softmax(z_logits).reshape(B, s.stoch * s.classes)
        feat = self.feat(h, z)
        return {"logits": self._tower(params["actor"], feat),
                "vf": dist_mean(self._tower(params["critic"], feat),
                                self.bins)}


# ------------------------------------------------------------------ learner
class DreamerLearner:
    """World model + actor + critic, one jitted update over a [B, L]
    sequence batch (reference: dreamerv3/dreamerv3_learner.py)."""

    def __init__(self, module_spec: DreamerModuleSpec, config: Dict,
                 use_mesh: bool = True):
        self.module = module_spec.build()
        self.config = config
        self._rng = jax.random.key(config.get("seed", 0))
        self._rng, init_key = jax.random.split(self._rng)
        self.params = self.module.init(init_key)
        self.slow_critic = jax.tree.map(jnp.copy, self.params["critic"])
        self.tx = optax.chain(
            optax.clip_by_global_norm(config.get("grad_clip", 100.0)),
            optax.adam(config.get("lr", 4e-4)))
        self.opt_state = self.tx.init(self.params)
        # percentile return-normalization state (EMA of the 5..95 range)
        self.ret_scale = jnp.asarray(1.0)
        self._update = jax.jit(self._build_update())

    # --- world model loss -------------------------------------------------
    def _wm_and_img(self, params, slow_critic, batch, ret_scale, rng):
        m = self.module
        s = m.spec
        cfg = self.config
        obs = batch["obs"]            # [B, L, D]
        actions = batch["actions"].astype(jnp.int32)   # [B, L]
        rewards = batch["rewards"]
        dones = batch["dones"]
        is_first = batch["is_first"]
        B, L = actions.shape
        a_onehot = jax.nn.one_hot(actions, s.action_dim)
        prev_a = jnp.concatenate(
            [jnp.zeros((B, 1, s.action_dim)), a_onehot[:, :-1]], 1)

        rng, scan_key = jax.random.split(rng)
        step_keys = jax.random.split(scan_key, L)

        def rssm_step(carry, t_in):
            h, z = carry
            obs_t, prev_a_t, first_t, key = t_in
            # episode boundary: reset the latent state rows
            keep = (1.0 - first_t)[:, None]
            h, z = h * keep, z * keep
            prev_a_t = prev_a_t * keep
            h = m.sequence_step(params, h, z, prev_a_t)
            post = m.post_logits(params, h, obs_t)
            prior = m.prior_logits(params, h)
            z = m._z_sample(post, key)
            return (h, z), (h, z, post, prior)

        h0 = jnp.zeros((B, s.deter))
        z0 = jnp.zeros((B, s.stoch * s.classes))
        # time-major scan over the sequence
        t_obs = jnp.swapaxes(obs, 0, 1)
        t_prev_a = jnp.swapaxes(prev_a, 0, 1)
        t_first = jnp.swapaxes(is_first, 0, 1)
        (_, _), (hs, zs, posts, priors) = jax.lax.scan(
            rssm_step, (h0, z0), (t_obs, t_prev_a, t_first, step_keys))
        # back to batch-major
        hs, zs = jnp.swapaxes(hs, 0, 1), jnp.swapaxes(zs, 0, 1)
        posts, priors = jnp.swapaxes(posts, 0, 1), \
            jnp.swapaxes(priors, 0, 1)
        feat = m.feat(hs, zs)                       # [B, L, F]

        # prediction losses (symlog decoder, twohot reward, bernoulli cont)
        obs_hat = m._tower(params["decoder"], feat)
        recon_loss = jnp.mean(jnp.sum(
            (obs_hat - symlog(obs)) ** 2, -1))
        r_logits = m._tower(params["reward"], feat)
        r_target = twohot(symlog(rewards), m.bins)
        reward_loss = -jnp.mean(jnp.sum(
            r_target * jax.nn.log_softmax(r_logits), -1))
        c_logits = m._tower(params["cont"], feat)[..., 0]
        cont_target = 1.0 - dones
        cont_loss = jnp.mean(
            jnp.maximum(c_logits, 0) - c_logits * cont_target
            + jnp.log1p(jnp.exp(-jnp.abs(c_logits))))

        # KL balancing with free bits (Hafner 2023 Eq. 5)
        def kl(p_logits, q_logits):
            p = jax.nn.softmax(p_logits)
            return jnp.sum(p * (jax.nn.log_softmax(p_logits)
                                - jax.nn.log_softmax(q_logits)), -1)

        dyn_kl = kl(jax.lax.stop_gradient(posts), priors).sum(-1)
        rep_kl = kl(posts, jax.lax.stop_gradient(priors)).sum(-1)
        free = cfg.get("free_bits", 1.0)
        dyn_loss = jnp.mean(jnp.maximum(dyn_kl, free))
        rep_loss = jnp.mean(jnp.maximum(rep_kl, free))
        wm_loss = recon_loss + reward_loss + cont_loss + \
            cfg.get("dyn_scale", 0.5) * dyn_loss + \
            cfg.get("rep_scale", 0.1) * rep_loss

        # ---- imagination from every posterior state (gradients stop at
        # the handoff: the world model is the actor's environment)
        H = cfg.get("imagine_horizon", 10)
        flat_h = jax.lax.stop_gradient(hs.reshape(-1, s.deter))
        flat_z = jax.lax.stop_gradient(
            zs.reshape(-1, s.stoch * s.classes))
        rng, img_key = jax.random.split(rng)
        img_keys = jax.random.split(img_key, H)

        def img_step(carry, key):
            h, z = carry
            feat_t = m.feat(h, z)
            a_logits = m._tower(params["actor"], feat_t)
            ka, kz = jax.random.split(key)
            a = jax.random.categorical(ka, a_logits)
            a_1h = jax.nn.one_hot(a, s.action_dim)
            h = m.sequence_step(params, h, z, a_1h)
            z = m._z_sample(m.prior_logits(params, h), kz)
            return (h, z), (feat_t, a, h, z)

        (_, _), (img_feat, img_a, img_h, img_z) = jax.lax.scan(
            img_step, (flat_h, flat_z), img_keys)
        # heads along the imagined trajectory [H, N, ...]
        img_feat_next = m.feat(img_h, img_z)
        r_pred = dist_mean(m._tower(params["reward"], img_feat_next),
                           m.bins)
        cont_pred = jax.nn.sigmoid(
            m._tower(params["cont"], img_feat_next)[..., 0])
        v = dist_mean(m._tower(params["critic"], img_feat_next), m.bins)
        gamma = cfg.get("gamma", 0.997) * cont_pred
        lam = cfg.get("lambda_", 0.95)

        def lam_step(nxt, t):
            ret = r_pred[t] + gamma[t] * ((1 - lam) * v[t] + lam * nxt)
            return ret, ret

        _, rets = jax.lax.scan(lam_step, v[-1],
                               jnp.arange(H - 1, -1, -1))
        rets = rets[::-1]                            # [H, N] lambda-returns

        # percentile normalization of returns (Hafner 2023 Sec. 3)
        lo = jnp.percentile(rets, 5)
        hi = jnp.percentile(rets, 95)
        new_scale = 0.99 * ret_scale + 0.01 * jnp.maximum(hi - lo, 1.0)

        # actor: reinforce with normalized advantage + entropy
        a_logits_all = m._tower(
            params["actor"], jax.lax.stop_gradient(img_feat))
        logp_all = jax.nn.log_softmax(a_logits_all)
        idx = jax.nn.one_hot(img_a, s.action_dim)
        logp_taken = jnp.sum(logp_all * idx, -1)
        v_base = dist_mean(m._tower(
            jax.lax.stop_gradient(params)["critic"],
            jax.lax.stop_gradient(img_feat)), m.bins)
        adv = jax.lax.stop_gradient((rets - v_base) / new_scale)
        entropy = -jnp.sum(jax.nn.softmax(a_logits_all) * logp_all, -1)
        actor_loss = -jnp.mean(logp_taken * adv) - \
            cfg.get("entropy_scale", 3e-3) * jnp.mean(entropy)

        # critic: twohot CE to lambda-returns + EMA regularizer
        c_logits_img = m._tower(params["critic"],
                                jax.lax.stop_gradient(img_feat))
        tgt = jax.lax.stop_gradient(twohot(symlog(rets), m.bins))
        critic_loss = -jnp.mean(jnp.sum(
            tgt * jax.nn.log_softmax(c_logits_img), -1))
        slow_logits = m._tower(slow_critic,
                               jax.lax.stop_gradient(img_feat))
        slow_tgt = jax.lax.stop_gradient(jax.nn.softmax(slow_logits))
        critic_loss += cfg.get("slow_reg", 1.0) * -jnp.mean(jnp.sum(
            slow_tgt * jax.nn.log_softmax(c_logits_img), -1))

        total = wm_loss + actor_loss + critic_loss
        metrics = {
            "wm_loss": wm_loss, "recon_loss": recon_loss,
            "reward_loss": reward_loss, "cont_loss": cont_loss,
            "dyn_kl": jnp.mean(dyn_kl), "rep_kl": jnp.mean(rep_kl),
            "actor_loss": actor_loss, "critic_loss": critic_loss,
            "imagined_return_mean": jnp.mean(rets),
            "return_scale": new_scale,
        }
        return total, (metrics, new_scale)

    def _build_update(self):
        def update(params, slow_critic, opt_state, ret_scale, batch, rng):
            rng, key = jax.random.split(rng)
            (loss, (metrics, new_scale)), grads = jax.value_and_grad(
                self._wm_and_img, has_aux=True)(
                    params, slow_critic, batch, ret_scale, key)
            updates, opt_state = self.tx.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            tau = self.config.get("slow_critic_tau", 0.02)
            slow_critic = jax.tree.map(
                lambda t, o: (1 - tau) * t + tau * o,
                slow_critic, params["critic"])
            metrics["total_loss"] = loss
            return params, slow_critic, opt_state, new_scale, metrics, rng

        return update

    def update(self, batch: Dict[str, np.ndarray]) -> Dict[str, float]:
        (self.params, self.slow_critic, self.opt_state, self.ret_scale,
         metrics, self._rng) = self._update(
            self.params, self.slow_critic, self.opt_state, self.ret_scale,
            batch, self._rng)
        return {k: float(v) for k, v in metrics.items()}

    # Learner duck-type
    def get_weights(self):
        return self.params

    def set_weights(self, weights) -> None:
        self.params = weights

    def get_state(self) -> Dict:
        return {"params": self.params, "slow_critic": self.slow_critic,
                "opt_state": self.opt_state, "ret_scale": self.ret_scale}

    def set_state(self, state: Dict) -> None:
        self.params = state["params"]
        self.slow_critic = state["slow_critic"]
        self.opt_state = state["opt_state"]
        self.ret_scale = state["ret_scale"]


# ------------------------------------------------------------------- config
class DreamerV3Config(AlgorithmConfig):
    def __init__(self, algo_class=None):
        super().__init__(algo_class=algo_class or DreamerV3)
        self.gamma = 0.997
        self.lambda_ = 0.95
        self.lr = 4e-4
        self.deter = 128
        self.stoch = 8
        self.classes = 8
        self.model_hidden = 128
        self.num_bins = 41
        self.imagine_horizon = 10
        self.free_bits = 1.0
        self.dyn_scale = 0.5
        self.rep_scale = 0.1
        self.entropy_scale = 3e-3
        self.slow_critic_tau = 0.02
        self.train_ratio = 64     # replayed steps trained per env step
        self.batch_length = 16
        self.batch_size_seqs = 8
        self.replay_capacity_seqs = 2000
        self.rollout_fragment_length = 16
        self.num_env_runners = 1
        self.num_envs_per_env_runner = 4

    def _training_keys(self):
        return {"lambda_", "deter", "stoch", "classes", "model_hidden",
                "num_bins", "imagine_horizon", "free_bits", "dyn_scale",
                "rep_scale", "entropy_scale", "slow_critic_tau",
                "train_ratio", "batch_length", "batch_size_seqs",
                "replay_capacity_seqs"}

    def module_spec(self) -> DreamerModuleSpec:
        base = super().module_spec()
        if not base.discrete:
            raise ValueError(
                "this DreamerV3 implements discrete action spaces")
        return DreamerModuleSpec(
            obs_dim=base.obs_dim, action_dim=base.action_dim,
            deter=self.deter, stoch=self.stoch, classes=self.classes,
            hidden=self.model_hidden, num_bins=self.num_bins)

    def learner_config_dict(self) -> Dict:
        return {"lr": self.lr, "seed": self.seed, "gamma": self.gamma,
                "lambda_": self.lambda_,
                "imagine_horizon": self.imagine_horizon,
                "free_bits": self.free_bits, "dyn_scale": self.dyn_scale,
                "rep_scale": self.rep_scale,
                "entropy_scale": self.entropy_scale,
                "slow_critic_tau": self.slow_critic_tau}


class DreamerV3(Algorithm):
    learner_cls = DreamerLearner

    @classmethod
    def get_default_config(cls):
        return DreamerV3Config(algo_class=cls)

    def setup(self, _config) -> None:
        super().setup(_config)
        cfg = self.config
        self.replay = SequenceReplayBuffer(cfg.replay_capacity_seqs,
                                           seed=cfg.seed)

    def training_step(self) -> Dict:
        cfg = self.config
        learner = self.learner_group.local_learner()
        weights_ref = ray_tpu.put(learner.get_weights())
        samples = self._sample_from_runners(weights_ref)
        new_steps = sum(s["env_steps"] for s in samples)
        for s in samples:
            T, E = s["rewards"].shape
            # is_first: step 0 of the fragment, or right after a done
            is_first = np.zeros((T, E), np.float32)
            is_first[0] = 1.0
            is_first[1:] = s["dones"][:-1]
            self.replay.add_sequences(
                {"obs": s["obs"], "actions": s["actions"],
                 "rewards": s["rewards"], "dones": s["dones"],
                 "is_first": is_first},
                state_in=s.get("state_in") or
                tuple(np.zeros((E, 1), np.float32)))
        metrics: Dict = {"env_steps_this_iter": new_steps}
        if len(self.replay) < cfg.batch_size_seqs:
            return metrics
        updates = max(1, int(new_steps * cfg.train_ratio
                             / (cfg.batch_size_seqs * cfg.batch_length)))
        for _ in range(updates):
            seq = self.replay.sample(cfg.batch_size_seqs)
            batch = {k: seq[k] for k in
                     ("obs", "actions", "rewards", "dones", "is_first")}
            metrics.update(learner.update(batch))
        metrics["replay_seqs"] = len(self.replay)
        metrics["updates_this_iter"] = updates
        return metrics
