"""Checkpoint: a directory of files, framework-agnostic (reference:
python/ray/train/_checkpoint.py). Sharded ``jax.Array`` pytrees get
first-class helpers (host-gather for small models, per-shard files for
FSDP-style layouts — orbax handles the real multi-host case)."""

from __future__ import annotations

import contextlib
import os
import pickle
import shutil
import tempfile
import uuid
from typing import Any, Dict, Iterator, Optional


class Checkpoint:
    """A reference to a directory of checkpoint data — local path OR remote
    URI (reference: ray.train.Checkpoint wraps (path, pyarrow filesystem),
    train/_internal/storage.py:99-111; here the scheme resolves a
    StorageBackend). Remote checkpoints download on ``as_directory()`` /
    ``to_directory()``; ``.path`` stays the URI."""

    def __init__(self, path: str):
        from ray_tpu._private.storage import is_remote_uri, local_path

        self.path = path if is_remote_uri(path) \
            else os.path.abspath(local_path(path))

    @property
    def is_remote(self) -> bool:
        from ray_tpu._private.storage import is_remote_uri

        return is_remote_uri(self.path)

    @classmethod
    def from_directory(cls, path: str) -> "Checkpoint":
        return cls(path)

    def to_directory(self, path: Optional[str] = None) -> str:
        """Materialize the checkpoint data into ``path`` (or a fresh temp
        dir) — downloads when remote."""
        dest = path or os.path.join(
            tempfile.gettempdir(), f"ckpt_{uuid.uuid4().hex[:8]}")
        if self.is_remote:
            from ray_tpu._private.storage import get_storage_backend

            get_storage_backend(self.path).download_dir(self.path, dest)
        elif os.path.abspath(dest) != self.path:
            shutil.copytree(self.path, dest, dirs_exist_ok=True)
        return dest

    @contextlib.contextmanager
    def as_directory(self) -> Iterator[str]:
        if self.is_remote:
            d = self.to_directory()
            try:
                yield d
            finally:
                shutil.rmtree(d, ignore_errors=True)
        else:
            yield self.path

    # -- dict convenience (reference keeps these on legacy Checkpoint) -----
    @classmethod
    def from_dict(cls, data: Dict[str, Any]) -> "Checkpoint":
        d = tempfile.mkdtemp(prefix="ckpt_")
        with open(os.path.join(d, "_dict.pkl"), "wb") as f:
            pickle.dump(data, f)
        return cls(d)

    def to_dict(self) -> Dict[str, Any]:
        with self.as_directory() as d:
            with open(os.path.join(d, "_dict.pkl"), "rb") as f:
                return pickle.load(f)

    def __repr__(self):
        return f"Checkpoint(path={self.path})"

    def __reduce__(self):
        return (Checkpoint, (self.path,))


class InStoreCheckpoint(Checkpoint):
    """A checkpoint whose payload lives in the object store, not on disk.

    Backed by one packed uint8 buffer (``train/_internal/util.pack_dir``
    layout) so writing it is a single zero-copy ``ray_tpu.put`` per
    worker and restoring it rides the broadcast-tree pull path — N new
    workers rehydrate in O(bytes), never touching disk. ``get_file``/
    ``files``/``to_dict`` read straight from the buffer; ``path`` (the
    disk-Checkpoint contract user loops rely on, e.g.
    ``open(os.path.join(ckpt.path, ...))``) lazily materializes the
    buffer into a local tempdir ONCE and caches it — restore transport
    stays disk-free, only file-insisting consumers pay a local write.
    """

    def __init__(self, buffer: Any, ref: Any = None, step: int = 0):
        self.buffer = buffer
        self.ref = ref
        self.step = int(step)
        hexid = ref.hex() if ref is not None else uuid.uuid4().hex
        # no storage-backend resolution: the payload never hits a scheme
        self.uri = f"memory://{hexid}"
        self._path: Optional[str] = None

    @property
    def path(self) -> str:
        if self._path is None:
            self._path = self.to_directory()
        return self._path

    @property
    def is_remote(self) -> bool:
        return False

    @classmethod
    def from_state(cls, files: Dict[str, Any], step: int = 0
                   ) -> "InStoreCheckpoint":
        """Build from {relpath: bytes-like} without touching disk."""
        from ray_tpu.train._internal.util import pack_files

        return cls(pack_files(files), step=step)

    @classmethod
    def from_directory(cls, path: str) -> "InStoreCheckpoint":
        from ray_tpu.train._internal.util import pack_dir

        return cls(pack_dir(path))

    def get_file(self, relpath: str) -> memoryview:
        """Zero-copy view of one packed file."""
        from ray_tpu.train._internal.util import unpack_file

        return unpack_file(self.buffer, relpath)

    def files(self) -> Dict[str, Any]:
        from ray_tpu.train._internal.util import unpack_index

        return unpack_index(self.buffer)

    def to_directory(self, path: Optional[str] = None) -> str:
        from ray_tpu.train._internal.util import unpack_to_dir

        dest = path or os.path.join(
            tempfile.gettempdir(), f"ckpt_{uuid.uuid4().hex[:8]}")
        return unpack_to_dir(self.buffer, dest)

    @contextlib.contextmanager
    def as_directory(self) -> Iterator[str]:
        # the cached lazy materialization (kept for the checkpoint's
        # lifetime, so repeated consumers don't re-unpack)
        yield self.path

    @classmethod
    def from_dict(cls, data: Dict[str, Any]) -> "InStoreCheckpoint":
        return cls.from_state({"_dict.pkl": pickle.dumps(data)})

    def to_dict(self) -> Dict[str, Any]:
        return pickle.loads(bytes(self.get_file("_dict.pkl")))

    def __repr__(self):
        n = len(memoryview(self.buffer).cast("B")) \
            if self.buffer is not None else 0
        return f"InStoreCheckpoint(step={self.step}, nbytes={n})"

    def __reduce__(self):
        import numpy as np

        return (_rebuild_in_store_checkpoint,
                (np.asarray(self.buffer), self.step))


def _rebuild_in_store_checkpoint(buffer, step):
    return InStoreCheckpoint(buffer, step=step)


def save_pytree(tree: Any, directory: str, name: str = "params") -> str:
    """Persist a jax pytree of (possibly sharded) arrays.

    Device arrays are host-gathered per-leaf (fully-addressable shards on
    this host); the flat leaves go into one .npz + a pickled treedef. For
    multi-host sharded state use orbax via ``save_pytree_orbax``.
    """
    import jax
    import numpy as np

    leaves, treedef = jax.tree.flatten(tree)
    os.makedirs(directory, exist_ok=True)
    arrays = {f"leaf_{i}": np.asarray(jax.device_get(x))
              for i, x in enumerate(leaves)}
    np.savez(os.path.join(directory, f"{name}.npz"), **arrays)
    with open(os.path.join(directory, f"{name}.treedef.pkl"), "wb") as f:
        pickle.dump(treedef, f)
    return directory


def load_pytree(directory: str, name: str = "params") -> Any:
    import jax
    import numpy as np

    with open(os.path.join(directory, f"{name}.treedef.pkl"), "rb") as f:
        treedef = pickle.load(f)
    data = np.load(os.path.join(directory, f"{name}.npz"))
    leaves = [data[f"leaf_{i}"] for i in range(len(data.files))]
    return jax.tree.unflatten(treedef, leaves)


def save_pytree_orbax(tree: Any, directory: str) -> str:
    """Sharded checkpoint via orbax (the real TPU path: each host writes its
    own shards; reference analog: StorageContext + framework checkpointing,
    train/_internal/storage.py:99-111)."""
    import orbax.checkpoint as ocp

    ckptr = ocp.StandardCheckpointer()
    ckptr.save(os.path.join(os.path.abspath(directory), "orbax"), tree,
               force=True)
    ckptr.wait_until_finished()
    return directory


def load_pytree_orbax(directory: str, like: Any) -> Any:
    import orbax.checkpoint as ocp

    ckptr = ocp.StandardCheckpointer()
    return ckptr.restore(os.path.join(os.path.abspath(directory), "orbax"),
                         like)
