"""BOHB + native TPE searcher (reference: tune/search/bohb/bohb_search.py:50
TuneBOHB, schedulers/hb_bohb.py; VERDICT r1 item 9 — BOHB reproduces
ASHA-or-better trial efficiency on a toy surface)."""

import math

import pytest

import ray_tpu
from ray_tpu import tune
from ray_tpu.air.config import RunConfig
from ray_tpu.tune import TuneConfig, Tuner
from ray_tpu.tune.schedulers import ASHAScheduler, HyperBandForBOHB
from ray_tpu.tune.search import TPESearcher, TuneBOHB
from ray_tpu.tune.search.sample import Categorical, Float


@pytest.fixture(scope="module")
def ray4():
    if not ray_tpu.is_initialized():
        ray_tpu.init(num_cpus=4)
    yield
    ray_tpu.shutdown()


def _surface(x, y):
    """Smooth toy objective, optimum at (0.7, -0.3), max value 10."""
    return 10.0 - 12.0 * ((x - 0.7) ** 2 + (y + 0.3) ** 2)


def _objective(config):
    for i in range(1, 10):
        # fidelity-dependent: low budgets see a noisy shifted surface,
        # converging toward the true one (the BOHB setting)
        frac = i / 9.0
        value = frac * _surface(config["x"], config["y"]) + \
            (1 - frac) * (5.0 - abs(config["x"]))
        tune.report({"score": value})


SPACE = {"x": tune.uniform(-2.0, 2.0), "y": tune.uniform(-2.0, 2.0)}


def test_tpe_exploits_on_pure_model_level():
    """Model sanity without a cluster: after seeing the toy surface, TPE's
    suggestions concentrate near the optimum vs uniform random."""
    searcher = TPESearcher(space=dict(SPACE), metric="score", mode="max",
                           n_initial_points=12, seed=7)
    import random

    rng = random.Random(3)
    for i in range(60):
        tid = f"t{i}"
        cfg = searcher.suggest(tid)
        searcher.on_trial_complete(
            tid, {"score": _surface(cfg["x"], cfg["y"])})
    searcher.epsilon = 0.0  # probe the model greedily
    tail = []
    for i in range(10):
        tid = f"probe{i}"
        cfg = searcher.suggest(tid)
        tail.append(math.hypot(cfg["x"] - 0.7, cfg["y"] + 0.3))
    random_dist = [math.hypot(rng.uniform(-2, 2) - 0.7,
                              rng.uniform(-2, 2) + 0.3)
                   for _ in range(1000)]
    avg_random = sum(random_dist) / len(random_dist)
    avg_tail = sum(tail) / len(tail)
    assert avg_tail < avg_random * 0.6, (avg_tail, avg_random)


def test_tpe_handles_categorical_and_log():
    space = {"lr": tune.loguniform(1e-5, 1e-1),
             "act": tune.choice(["relu", "gelu", "tanh"])}
    searcher = TPESearcher(space=space, metric="score", mode="max",
                           n_initial_points=10, seed=11)
    for i in range(80):
        tid = f"t{i}"
        cfg = searcher.suggest(tid)
        score = (5.0 if cfg["act"] == "gelu" else 0.0) - \
            abs(math.log10(cfg["lr"]) + 3.0)  # best: gelu, lr=1e-3
        searcher.on_trial_complete(tid, {"score": score})
    searcher.epsilon = 0.0  # probe the model greedily
    hits = 0
    for i in range(10):
        cfg = searcher.suggest(f"p{i}")
        if cfg["act"] == "gelu" and 1e-4 < cfg["lr"] < 1e-2:
            hits += 1
    assert hits >= 5, hits


def test_bohb_end_to_end_beats_or_matches_asha(ray4, tmp_path):
    """Same trial budget: BOHB's model-guided search must find a best
    score at least as good as ASHA + random within tolerance, and
    early-stop some trials (trial efficiency)."""
    n_samples = 32

    def run(name, scheduler, searcher):
        tuner = Tuner(
            _objective,
            param_space=dict(SPACE),
            tune_config=TuneConfig(
                metric="score", mode="max", num_samples=n_samples,
                max_concurrent_trials=4, scheduler=scheduler,
                search_alg=searcher),
            run_config=RunConfig(name=name, storage_path=str(tmp_path)),
        )
        results = tuner.fit()
        best = results.get_best_result().metrics["score"]
        iters = [r.metrics["training_iteration"] for r in results]
        return best, iters

    bohb_best, bohb_iters = run(
        "bohb",
        HyperBandForBOHB(max_t=9, reduction_factor=3),
        TuneBOHB(metric="score", mode="max", n_initial_points=8, seed=5))
    asha_best, _ = run(
        "asha",
        ASHAScheduler(max_t=9, grace_period=1, reduction_factor=3),
        None)

    # concurrency makes observation order (and thus the exact model state)
    # nondeterministic, so quality parity uses a generous tolerance — the
    # precise exploitation claims live in the deterministic model-level
    # tests above
    assert bohb_best >= asha_best - 3.0, (bohb_best, asha_best)
    assert bohb_best > 6.0, bohb_best          # clearly better than noise
    assert min(bohb_iters) < 9, bohb_iters     # early stopping happened
    # trial efficiency: meaningfully below the exhaustive budget
    assert sum(bohb_iters) <= 0.85 * n_samples * 9, sum(bohb_iters)
