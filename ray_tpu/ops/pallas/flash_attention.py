"""Flash attention (forward + backward) as Pallas TPU kernels.

Online-softmax blocked attention: the kv axis is the innermost grid dim, and
running (max, sum, acc) state lives in VMEM scratch that persists across the
sequential TPU grid — the classic FlashAttention-2 schedule mapped onto
Pallas. Causal blocks above the diagonal are skipped with ``pl.when`` (zero
MXU work, the DMA still runs; a fused skip via index_map is a later
optimization).

The forward also emits the per-row logsumexp; the backward recomputes block
scores against it in two kernels (dq with kv innermost; dk/dv with q
innermost), so neither pass materializes [S, S] in HBM — this is what makes
flash usable for TRAINING, where the naive vjp through reference attention
would dominate the step at seq >= 2k.

GQA is handled in the index maps throughout: the forward and dq read
kv head = q head // n_rep; the dk/dv kernel's grid walks each kv head's
whole query group (an extra sequential grid dim), accumulating the group's
contributions in VMEM scratch — so GQA models (Llama-3-class) train under
flash instead of falling back to blockwise attention.
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_NEG_INF = -1e30


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, m_scr, l_scr, acc_scr,
                *, scale: float, causal: bool, block_q: int, block_k: int):
    iq = pl.program_id(2)
    ik = pl.program_id(3)
    nk = pl.num_programs(3)

    @pl.when(ik == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, _NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    # Skip fully-masked blocks (strictly above the causal diagonal).
    run = True
    if causal:
        run = ik * block_k <= iq * block_q + block_q - 1

    @pl.when(run)
    def _compute():
        q = q_ref[0, 0].astype(jnp.float32)            # [bq, d]
        k = k_ref[0, 0].astype(jnp.float32)            # [bk, d]
        v = v_ref[0, 0].astype(jnp.float32)            # [bk, d]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale   # [bq, bk]
        if causal:
            q_pos = iq * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            k_pos = ik * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(q_pos >= k_pos, s, _NEG_INF)
        m_prev = m_scr[:, :1]                        # [bq, 1]
        m_cur = jnp.max(s, axis=-1, keepdims=True)   # [bq, 1]
        m_new = jnp.maximum(m_prev, m_cur)
        p = jnp.exp(s - m_new)                       # [bq, bk]
        alpha = jnp.exp(m_prev - m_new)              # [bq, 1]
        l_new = alpha * l_scr[:, :1] + jnp.sum(p, axis=-1, keepdims=True)
        acc_scr[:] = acc_scr[:] * alpha + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_scr[:] = jnp.broadcast_to(m_new, m_scr.shape)
        l_scr[:] = jnp.broadcast_to(l_new, l_scr.shape)

    @pl.when(ik == nk - 1)
    def _finalize():
        l = l_scr[:, :1]
        o_ref[0, 0] = (acc_scr[:] / jnp.maximum(l, 1e-30)).astype(o_ref.dtype)
        # logsumexp row stats for the backward (lse layout [bq, 128]: the
        # row value broadcast across lanes — keeps stores 2D/tiled)
        lse_ref[0, 0] = jnp.broadcast_to(
            m_scr[:, :1] + jnp.log(jnp.maximum(l, 1e-30)),
            lse_ref[0, 0].shape)


def _flash_fwd(q: jax.Array, k: jax.Array, v: jax.Array, *,
               causal: bool, block_q: int, block_k: int
               ) -> Tuple[jax.Array, jax.Array]:
    """q [B,H,S,D], k/v [B,KVH,S,D] → (o [B,H,S,D], lse [B,H,S,128])."""
    B, H, Sq, D = q.shape
    KVH, Skv = k.shape[1], k.shape[2]
    n_rep = H // KVH
    scale = D ** -0.5
    block_q = next(b for b in (block_q, 512, 256, 128)
                   if Sq % b == 0 or b == 128)
    block_k = next(b for b in (block_k, 512, 256, 128)
                   if Skv % b == 0 or b == 128)
    if Sq % block_q or Skv % block_k:
        raise ValueError(f"seq lens ({Sq},{Skv}) must divide by 128")
    grid = (B, H, Sq // block_q, Skv // block_k)

    kernel = functools.partial(
        _fwd_kernel, scale=scale, causal=causal,
        block_q=block_q, block_k=block_k)

    return pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, 1, block_q, D),
                         lambda b, h, iq, ik: (b, h, iq, 0)),
            pl.BlockSpec((1, 1, block_k, D),
                         lambda b, h, iq, ik: (b, h // n_rep, ik, 0)),
            pl.BlockSpec((1, 1, block_k, D),
                         lambda b, h, iq, ik: (b, h // n_rep, ik, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, 1, block_q, D),
                         lambda b, h, iq, ik: (b, h, iq, 0)),
            pl.BlockSpec((1, 1, block_q, 128),
                         lambda b, h, iq, ik: (b, h, iq, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B, H, Sq, D), q.dtype),
            jax.ShapeDtypeStruct((B, H, Sq, 128), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, 128), jnp.float32),   # running max
            pltpu.VMEM((block_q, 128), jnp.float32),   # running sum
            pltpu.VMEM((block_q, D), jnp.float32),     # accumulator
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "parallel",
                                 "arbitrary")),
        interpret=jax.devices()[0].platform != "tpu",
    )(q, k, v)


# ----------------------------------------------------------------- backward

def _dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref,
               dq_scr, *, scale: float, causal: bool, block_q: int,
               block_k: int):
    """Grid (B, H, iq, ik): kv innermost, accumulate dq for one q block."""
    iq = pl.program_id(2)
    ik = pl.program_id(3)
    nk = pl.num_programs(3)

    @pl.when(ik == 0)
    def _init():
        dq_scr[:] = jnp.zeros_like(dq_scr)

    run = True
    if causal:
        run = ik * block_k <= iq * block_q + block_q - 1

    @pl.when(run)
    def _compute():
        q = q_ref[0, 0].astype(jnp.float32)                 # [bq, d]
        k = k_ref[0, 0].astype(jnp.float32)                 # [bk, d]
        v = v_ref[0, 0].astype(jnp.float32)                 # [bk, d]
        do = do_ref[0, 0].astype(jnp.float32)               # [bq, d]
        lse = lse_ref[0, 0][:, :1]                          # [bq, 1]
        delta = delta_ref[0, 0][:, :1]                      # [bq, 1]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale     # [bq, bk]
        if causal:
            q_pos = iq * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            k_pos = ik * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(q_pos >= k_pos, s, _NEG_INF)
        p = jnp.exp(s - lse)                                # [bq, bk]
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)             # [bq, bk]
        ds = p * (dp - delta) * scale                       # [bq, bk]
        dq_scr[:] = dq_scr[:] + jax.lax.dot_general(
            ds, k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)             # [bq, d]

    @pl.when(ik == nk - 1)
    def _finalize():
        dq_ref[0, 0] = dq_scr[:].astype(dq_ref.dtype)


def _dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                dk_ref, dv_ref, dk_scr, dv_scr, *, scale: float,
                causal: bool, block_q: int, block_k: int):
    """Grid (B, KVH, ik, r, iq): q-head-in-group then q blocks innermost,
    accumulating dk/dv for one kv block across the WHOLE q-head group —
    this is the GQA backward (n_rep > 1): each kv head's gradient sums
    contributions from its n_rep query heads (VERDICT r2 item 6)."""
    ik = pl.program_id(2)
    r = pl.program_id(3)
    iq = pl.program_id(4)
    n_rep = pl.num_programs(3)
    nq = pl.num_programs(4)

    @pl.when(jnp.logical_and(r == 0, iq == 0))
    def _init():
        dk_scr[:] = jnp.zeros_like(dk_scr)
        dv_scr[:] = jnp.zeros_like(dv_scr)

    run = True
    if causal:  # block needed iff some q row >= first k row
        run = iq * block_q + block_q - 1 >= ik * block_k

    @pl.when(run)
    def _compute():
        q = q_ref[0, 0].astype(jnp.float32)                 # [bq, d]
        k = k_ref[0, 0].astype(jnp.float32)                 # [bk, d]
        v = v_ref[0, 0].astype(jnp.float32)                 # [bk, d]
        do = do_ref[0, 0].astype(jnp.float32)               # [bq, d]
        lse = lse_ref[0, 0][:, :1]                          # [bq, 1]
        delta = delta_ref[0, 0][:, :1]                      # [bq, 1]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale     # [bq, bk]
        if causal:
            q_pos = iq * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            k_pos = ik * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(q_pos >= k_pos, s, _NEG_INF)
        p = jnp.exp(s - lse)                                # [bq, bk]
        # dv += p^T do
        dv_scr[:] = dv_scr[:] + jax.lax.dot_general(
            p, do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)             # [bk, d]
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)             # [bq, bk]
        ds = p * (dp - delta) * scale                       # [bq, bk]
        # dk += ds^T q
        dk_scr[:] = dk_scr[:] + jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)             # [bk, d]

    @pl.when(jnp.logical_and(r == n_rep - 1, iq == nq - 1))
    def _finalize():
        dk_ref[0, 0] = dk_scr[:].astype(dk_ref.dtype)
        dv_ref[0, 0] = dv_scr[:].astype(dv_ref.dtype)


def _flash_bwd(q, k, v, o, lse, do, *, causal: bool, block_q: int,
               block_k: int):
    """q/o/do [B,H,Sq,D], k/v [B,KVH,Skv,D] (lse [B,H,Sq,128]); returns
    (dq [B,H,Sq,D], dk/dv [B,KVH,Skv,D]). GQA (KVH < H) is handled in the
    index maps: dq reads kv head h//n_rep; dk/dv accumulate across the
    n_rep query heads of their group inside the kernel grid."""
    B, H, Sq, D = q.shape
    KVH, Skv = k.shape[1], k.shape[2]
    n_rep = H // KVH
    scale = D ** -0.5
    block_q = next(b for b in (block_q, 512, 256, 128)
                   if Sq % b == 0 or b == 128)
    block_k = next(b for b in (block_k, 512, 256, 128)
                   if Skv % b == 0 or b == 128)

    # delta_i = rowsum(dO_i * O_i) — cheap elementwise, stays in XLA;
    # broadcast across 128 lanes to match the lse layout
    delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32), axis=-1)
    delta = jnp.broadcast_to(delta[..., None], (*delta.shape, 128))

    q_spec = pl.BlockSpec((1, 1, block_q, D), lambda b, h, i, j: (b, h, i, 0))
    row_spec = pl.BlockSpec((1, 1, block_q, 128),
                            lambda b, h, i, j: (b, h, i, 0))

    dq = pl.pallas_call(
        functools.partial(_dq_kernel, scale=scale, causal=causal,
                          block_q=block_q, block_k=block_k),
        grid=(B, H, Sq // block_q, Skv // block_k),
        in_specs=[
            q_spec,
            pl.BlockSpec((1, 1, block_k, D),
                         lambda b, h, i, j: (b, h // n_rep, j, 0)),
            pl.BlockSpec((1, 1, block_k, D),
                         lambda b, h, i, j: (b, h // n_rep, j, 0)),
            q_spec, row_spec, row_spec,
        ],
        out_specs=q_spec,
        out_shape=jax.ShapeDtypeStruct((B, H, Sq, D), q.dtype),
        scratch_shapes=[pltpu.VMEM((block_q, D), jnp.float32)],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "parallel",
                                 "arbitrary")),
        interpret=jax.devices()[0].platform != "tpu",
    )(q, k, v, do, lse, delta)

    # dk/dv: grid (B, KVH, ik, r, iq) — r walks the kv head's query group
    kv_spec = pl.BlockSpec((1, 1, block_k, D),
                           lambda b, hk, i, r, j: (b, hk, i, 0))
    qg_spec = pl.BlockSpec((1, 1, block_q, D),
                           lambda b, hk, i, r, j: (b, hk * n_rep + r, j, 0))
    qg_row = pl.BlockSpec((1, 1, block_q, 128),
                          lambda b, hk, i, r, j: (b, hk * n_rep + r, j, 0))
    dk, dv = pl.pallas_call(
        functools.partial(_dkv_kernel, scale=scale, causal=causal,
                          block_q=block_q, block_k=block_k),
        grid=(B, KVH, Skv // block_k, n_rep, Sq // block_q),
        in_specs=[qg_spec, kv_spec, kv_spec, qg_spec, qg_row, qg_row],
        out_specs=[kv_spec, kv_spec],
        out_shape=[
            jax.ShapeDtypeStruct((B, KVH, Skv, D), k.dtype),
            jax.ShapeDtypeStruct((B, KVH, Skv, D), v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_k, D), jnp.float32),
            pltpu.VMEM((block_k, D), jnp.float32),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "parallel",
                                 "arbitrary", "arbitrary")),
        interpret=jax.devices()[0].platform != "tpu",
    )(q, k, v, do, lse, delta)
    return dq, dk, dv


# Kernel takes [B,H,S,D]; public API is [B,S,H,D] to match ops.attention.

@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def flash_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                    causal: bool = True) -> jax.Array:
    qt = jnp.swapaxes(q, 1, 2)
    kt = jnp.swapaxes(k, 1, 2)
    vt = jnp.swapaxes(v, 1, 2)
    o, _ = _flash_fwd(qt, kt, vt, causal=causal, block_q=512, block_k=512)
    return jnp.swapaxes(o, 1, 2)


def _fa_fwd(q, k, v, causal):
    qt = jnp.swapaxes(q, 1, 2)
    kt = jnp.swapaxes(k, 1, 2)
    vt = jnp.swapaxes(v, 1, 2)
    o, lse = _flash_fwd(qt, kt, vt, causal=causal, block_q=512, block_k=512)
    return jnp.swapaxes(o, 1, 2), (qt, kt, vt, o, lse)


def _fa_bwd(causal, res, g):
    qt, kt, vt, o, lse = res
    do = jnp.swapaxes(g, 1, 2)
    dq, dk, dv = _flash_bwd(qt, kt, vt, o, lse, do, causal=causal,
                            block_q=512, block_k=512)
    return (jnp.swapaxes(dq, 1, 2), jnp.swapaxes(dk, 1, 2),
            jnp.swapaxes(dv, 1, 2))


flash_attention.defvjp(_fa_fwd, _fa_bwd)
