"""In-process memory store for small objects.

Parity with the reference's core-worker memory store (reference:
``src/ray/core_worker/store_provider/memory_store/memory_store.h``): small
task returns and errors skip shared memory entirely and resolve ``get``/
``wait`` directly in the owner process.

Waits are targeted: each waiter registers the exact ids it is missing, and a
``put`` wakes only waiters it satisfies. The naive broadcast alternative wakes
every blocked ``get`` on every unrelated ``put`` — O(n²) context switches when
a driver gathers a large batch of task returns on a loaded box.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, List, Optional, Tuple


class _Entry:
    __slots__ = ("data", "is_exception")

    def __init__(self, data: bytes, is_exception: bool):
        self.data = data
        self.is_exception = is_exception


class _Waiter:
    __slots__ = ("missing", "need_more", "event")

    def __init__(self, missing: set, need_more: int):
        self.missing = missing      # ids not yet present
        self.need_more = need_more  # how many more arrivals satisfy the wait
        self.event = threading.Event()


class MemoryStore:
    def __init__(self):
        # RLock, NOT Lock: ObjectRef.__del__ (GC-triggered, any thread,
        # any bytecode boundary) reaches delete() via the reference
        # counter. A garbage cycle collected while THIS thread is inside
        # a critical section — e.g. wait() allocating its _Waiter —
        # would deadlock the whole process on a plain Lock (observed:
        # driver wedged in wait → __del__ → delete with the io thread
        # stuck behind it in put). Re-entry is safe: every method does
        # point dict/list operations.
        self._lock = threading.RLock()
        self._objects: Dict[bytes, _Entry] = {}
        self._waiters: List[_Waiter] = []
        # coarse completion hooks (no per-id filtering): pollers that
        # sleep between scans — the streaming executor's event-paced
        # drive loop (ISSUE 12) — register a callback instead of
        # busy-polling. Called OUTSIDE the lock, must be cheap and
        # exception-free (Event.set).
        self._put_listeners: List = []

    def add_put_listener(self, cb) -> None:
        with self._lock:
            if cb not in self._put_listeners:
                self._put_listeners.append(cb)

    def remove_put_listener(self, cb) -> None:
        with self._lock:
            try:
                self._put_listeners.remove(cb)
            except ValueError:
                pass

    def put(self, object_id: bytes, data: bytes, is_exception: bool = False) -> None:
        wake: List[_Waiter] = []
        with self._lock:
            self._objects[object_id] = _Entry(data, is_exception)
            listeners = tuple(self._put_listeners)
            if self._waiters:
                still = []
                for w in self._waiters:
                    if object_id in w.missing:
                        w.missing.discard(object_id)
                        w.need_more -= 1
                        if w.need_more <= 0:
                            wake.append(w)
                            continue
                    still.append(w)
                self._waiters = still
        for w in wake:
            w.event.set()
        for cb in listeners:
            try:
                cb()
            except Exception:
                pass

    def put_batch(self, entries: List[Tuple[bytes, bytes, bool]]) -> None:
        """Many puts under ONE lock acquisition and one waiter pass — the
        delivery end of the batched completion queue (ISSUE 18): a frame
        of task replies resolving together costs one scan of the waiter
        list instead of one per return."""
        if not entries:
            return
        if len(entries) == 1:
            oid, data, is_exc = entries[0]
            self.put(oid, data, is_exc)
            return
        wake: List[_Waiter] = []
        with self._lock:
            objects = self._objects
            for oid, data, is_exc in entries:
                objects[oid] = _Entry(data, is_exc)
            listeners = tuple(self._put_listeners)
            if self._waiters:
                ids = {e[0] for e in entries}
                still = []
                for w in self._waiters:
                    hit = w.missing & ids
                    if hit:
                        w.missing -= hit
                        w.need_more -= len(hit)
                        if w.need_more <= 0:
                            wake.append(w)
                            continue
                    still.append(w)
                self._waiters = still
        for w in wake:
            w.event.set()
        for cb in listeners:
            try:
                cb()
            except Exception:
                pass

    def contains(self, object_id: bytes) -> bool:
        with self._lock:
            return object_id in self._objects

    def get(self, object_id: bytes) -> Optional[Tuple[bytes, bool]]:
        with self._lock:
            e = self._objects.get(object_id)
            return (e.data, e.is_exception) if e else None

    def delete(self, object_id: bytes) -> None:
        with self._lock:
            self._objects.pop(object_id, None)

    def wait(
        self, object_ids: List[bytes], num_returns: int, timeout: Optional[float]
    ) -> Tuple[List[bytes], List[bytes]]:
        """Block until num_returns of object_ids are present (or timeout)."""
        if len(object_ids) > 1 and len(set(object_ids)) != len(object_ids):
            # duplicates would double-count toward need_more and hang the wait
            object_ids = list(dict.fromkeys(object_ids))
            num_returns = min(num_returns, len(object_ids))
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            with self._lock:
                ready = [oid for oid in object_ids if oid in self._objects]
                if len(ready) >= num_returns:
                    ready = ready[:num_returns]
                    ready_set = set(ready)
                    remaining = [o for o in object_ids if o not in ready_set]
                    return ready, remaining
                waiter = _Waiter(
                    {o for o in object_ids if o not in self._objects},
                    num_returns - len(ready),
                )
                self._waiters.append(waiter)
            left = None if deadline is None else deadline - time.monotonic()
            if left is not None and left <= 0:
                satisfied = False
            else:
                satisfied = waiter.event.wait(left)
            with self._lock:
                if waiter in self._waiters:
                    self._waiters.remove(waiter)
            if not satisfied and (deadline is not None
                                  and time.monotonic() >= deadline):
                with self._lock:
                    ready = [oid for oid in object_ids if oid in self._objects]
                ready = ready[:num_returns]
                ready_set = set(ready)
                remaining = [o for o in object_ids if o not in ready_set]
                return ready, remaining
            # satisfied (or spurious): loop re-checks under the lock

    def size(self) -> int:
        with self._lock:
            return len(self._objects)
