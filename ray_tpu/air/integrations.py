"""Experiment-tracker integrations (reference: python/ray/air/
integrations/{wandb,mlflow,comet}.py — logger callbacks streaming trial
results to the tracking service).

All three are gated: none of the client libraries are in this image's
baked package set, so constructing a callback raises a clear ImportError;
when the library IS present the callback streams per-trial metrics.
"""

from __future__ import annotations

import numbers
from typing import Dict, Optional

from ray_tpu.tune.logger import LoggerCallback, _flatten


def _flat_numbers(d: Dict) -> Dict[str, float]:
    return {k: float(v) for k, v in _flatten(d).items()
            if isinstance(v, numbers.Number)}


class _WandbLoggingActorImpl:
    """Owns exactly one wandb.init() for its whole life, so concurrent
    trials can never finish or cross-wire each other's runs (reference:
    air/integrations/wandb.py runs a _WandbLoggingActor per trial). An
    actor — a clean worker process — avoids both os.fork of the
    multithreaded tune driver (copied held locks can deadlock the child)
    and spawn's __main__ re-import of unguarded user scripts."""

    def __init__(self, init_kwargs: Dict):
        import wandb

        self._run = wandb.init(**init_kwargs)

    def ready(self) -> bool:
        return True

    def log(self, metrics: Dict) -> None:
        try:
            self._run.log(metrics)
        except Exception:
            pass

    def finish(self) -> bool:
        self._run.finish()
        return True


class _WandbTrialProcess:
    """One logging actor per trial."""

    def __init__(self, init_kwargs: Dict):
        import ray_tpu

        self._actor = ray_tpu.remote(_WandbLoggingActorImpl).options(
            num_cpus=0).remote(init_kwargs)
        # surface init failures (bad API key, no network) in the driver,
        # like the pre-process-isolation code did
        ray_tpu.get(self._actor.ready.remote(), timeout=180)

    def log(self, metrics: Dict) -> None:
        self._actor.log.remote(metrics)  # fire and forget, ordered

    def finish(self) -> None:
        import ray_tpu

        try:
            ray_tpu.get(self._actor.finish.remote(), timeout=60)
        except Exception:
            pass
        finally:
            try:
                ray_tpu.kill(self._actor)
            except Exception:
                pass


class WandbLoggerCallback(LoggerCallback):
    """reference: air/integrations/wandb.py WandbLoggerCallback.

    Each trial logs through its own spawned wandb process — wandb.init in
    the shared driver process is not concurrency-safe (a second init
    finishes the first trial's active run)."""

    def __init__(self, project: Optional[str] = None,
                 group: Optional[str] = None, **kwargs):
        try:
            import wandb  # noqa: F401
        except ImportError as e:
            raise ImportError(
                "WandbLoggerCallback requires `wandb`, which is not "
                "installed in this environment. CSV/JSON loggers run by "
                "default; TBXLoggerCallback works with torch's "
                "tensorboard.") from e
        self.project = project
        self.group = group
        self.kwargs = kwargs
        self._runs: Dict[str, _WandbTrialProcess] = {}

    def log_trial_start(self, trial) -> None:
        self._runs[trial.trial_id] = _WandbTrialProcess(dict(
            project=self.project, group=self.group, name=trial.trial_id,
            config=dict(trial.config), **self.kwargs))

    def log_trial_result(self, trial, result: Dict) -> None:
        run = self._runs.get(trial.trial_id)
        if run is not None:
            run.log(_flat_numbers(result))

    def log_trial_end(self, trial) -> None:
        run = self._runs.pop(trial.trial_id, None)
        if run is not None:
            run.finish()


class MLflowLoggerCallback(LoggerCallback):
    """reference: air/integrations/mlflow.py MLflowLoggerCallback.

    Uses MlflowClient with explicit run ids (NOT the global active-run
    stack) so concurrent trials can't cross-write each other's runs."""

    def __init__(self, tracking_uri: Optional[str] = None,
                 experiment_name: Optional[str] = None, **kwargs):
        try:
            import mlflow  # noqa: F401
        except ImportError as e:
            raise ImportError(
                "MLflowLoggerCallback requires `mlflow`, which is not "
                "installed in this environment.") from e
        from mlflow.tracking import MlflowClient

        self._client = MlflowClient(tracking_uri=tracking_uri)
        self._experiment_id = "0"
        if experiment_name:
            exp = self._client.get_experiment_by_name(experiment_name)
            self._experiment_id = (exp.experiment_id if exp else
                                   self._client.create_experiment(
                                       experiment_name))
        self._runs: Dict[str, str] = {}  # trial_id -> mlflow run_id

    def log_trial_start(self, trial) -> None:
        run = self._client.create_run(
            self._experiment_id, run_name=trial.trial_id)
        self._runs[trial.trial_id] = run.info.run_id
        for k, v in trial.config.items():
            self._client.log_param(run.info.run_id, k, str(v))

    def log_trial_result(self, trial, result: Dict) -> None:
        run_id = self._runs.get(trial.trial_id)
        if run_id is None:
            return
        step = int(result.get("training_iteration", 0))
        for k, v in _flat_numbers(result).items():
            self._client.log_metric(run_id, k.replace("/", "."), v,
                                    step=step)

    def log_trial_end(self, trial) -> None:
        run_id = self._runs.pop(trial.trial_id, None)
        if run_id is not None:
            self._client.set_terminated(run_id)


class CometLoggerCallback(LoggerCallback):
    """reference: air/integrations/comet.py CometLoggerCallback."""

    def __init__(self, project_name: Optional[str] = None, **kwargs):
        try:
            import comet_ml  # noqa: F401
        except ImportError as e:
            raise ImportError(
                "CometLoggerCallback requires `comet_ml`, which is not "
                "installed in this environment.") from e
        self.project_name = project_name
        self.kwargs = kwargs
        self._experiments: Dict[str, object] = {}

    def log_trial_start(self, trial) -> None:
        import comet_ml

        exp = comet_ml.Experiment(project_name=self.project_name,
                                  **self.kwargs)
        exp.set_name(trial.trial_id)
        exp.log_parameters(dict(trial.config))
        self._experiments[trial.trial_id] = exp

    def log_trial_result(self, trial, result: Dict) -> None:
        exp = self._experiments.get(trial.trial_id)
        if exp is not None:
            exp.log_metrics(_flat_numbers(result),
                            step=int(result.get("training_iteration", 0)))

    def log_trial_end(self, trial) -> None:
        exp = self._experiments.pop(trial.trial_id, None)
        if exp is not None:
            exp.end()
