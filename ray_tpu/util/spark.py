"""Spark-on-ray_tpu launcher (reference: python/ray/util/spark/ —
setup_ray_cluster starting cluster nodes inside spark executors; here the
direction is inverted like `raydp`: run spark over the framework's
cluster).

Gated: `pyspark` is not in this image's baked package set; construction
raises a clear ImportError. The supported surface mirrors the reference's
module entry points so callers can feature-detect."""

from __future__ import annotations

from typing import Dict, Optional


def _require_pyspark():
    try:
        import pyspark  # noqa: F401
    except ImportError as e:
        raise ImportError(
            "ray_tpu.util.spark requires `pyspark`, which is not "
            "installed in this environment. Use ray_tpu.data for "
            "dataframe-style distributed processing instead.") from e


def setup_ray_cluster(num_worker_nodes: int,
                      num_cpus_per_node: Optional[int] = None,
                      **kwargs) -> Dict:
    """Reference: util/spark/cluster_init.py setup_ray_cluster."""
    _require_pyspark()
    raise NotImplementedError(
        "spark cluster integration requires a spark deployment")


def shutdown_ray_cluster() -> None:
    _require_pyspark()
