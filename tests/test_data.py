"""ray_tpu.data tests (model: python/ray/data/tests/ suites —
test_dataset*, test_map, test_all_to_all, test_consumption)."""

import os
import tempfile

import numpy as np
import pytest

import ray_tpu
import ray_tpu.data as rd
from ray_tpu.data.aggregate import Count, Max, Mean, Min, Std, Sum


@pytest.fixture(scope="module")
def data_cluster():
    if not ray_tpu.is_initialized():
        ray_tpu.init(num_cpus=4)
    yield
    ray_tpu.shutdown()


def test_range_count_schema(data_cluster):
    ds = rd.range(100, parallelism=4)
    assert ds.count() == 100
    assert ds.schema() == {"id": "int64"}
    assert ds.num_blocks() == 4


def test_map_batches_fusion(data_cluster):
    ds = rd.range(50, parallelism=2).map_batches(
        lambda b: {"id": b["id"] * 2}).map_batches(
        lambda b: {"id": b["id"] + 1})
    rows = ds.take_all()
    assert sorted(r["id"] for r in rows) == [2 * i + 1 for i in range(50)]
    # both map stages fused into the read: one op in the plan
    from ray_tpu.data._internal.planner import optimize

    ops = optimize(ds._last_op.chain())
    assert len(ops) == 1, [o.name for o in ops]


def test_map_filter_flat_map(data_cluster):
    ds = rd.range(20, parallelism=2)
    assert ds.map(lambda r: {"x": r["id"] ** 2}).take(3) == [
        {"x": 0}, {"x": 1}, {"x": 4}]
    assert ds.filter(lambda r: r["id"] % 2 == 0).count() == 10
    assert ds.flat_map(lambda r: [r, r]).count() == 40


def test_take_and_limit(data_cluster):
    ds = rd.range(1000, parallelism=8)
    assert [r["id"] for r in ds.take(5)] == [0, 1, 2, 3, 4]
    assert ds.limit(7).count() == 7


def test_columns_ops(data_cluster):
    ds = rd.range(10).add_column("y", lambda b: b["id"] * 3)
    assert ds.take(2) == [{"id": 0, "y": 0}, {"id": 1, "y": 3}]
    assert ds.select_columns(["y"]).columns() == ["y"]
    assert ds.drop_columns(["y"]).columns() == ["id"]
    assert ds.rename_columns({"id": "key"}).columns()[0] == "key"


def test_aggregates(data_cluster):
    ds = rd.range(100, parallelism=4)
    assert ds.sum("id") == 4950
    assert ds.min("id") == 0
    assert ds.max("id") == 99
    assert abs(ds.mean("id") - 49.5) < 1e-9
    vals = np.arange(100)
    assert abs(ds.std("id") - vals.std(ddof=1)) < 1e-6
    c, s = ds.aggregate(Count(), Sum("id"))
    assert (c, s) == (100, 4950)


def test_groupby(data_cluster):
    ds = rd.from_items([{"k": i % 3, "v": i} for i in range(30)])
    out = {r["k"]: r["sum(v)"] for r in ds.groupby("k").sum("v").take_all()}
    assert out == {0: 135, 1: 145, 2: 155}
    counts = {r["k"]: r["count()"]
              for r in ds.groupby("k").count().take_all()}
    assert counts == {0: 10, 1: 10, 2: 10}


def test_sort_shuffle_repartition(data_cluster):
    ds = rd.range(50, parallelism=5).random_shuffle(seed=7)
    ids = [r["id"] for r in ds.take_all()]
    assert sorted(ids) == list(range(50))
    assert ids != list(range(50))  # actually shuffled
    back = [r["id"] for r in ds.sort("id").take_all()]
    assert back == list(range(50))
    desc = [r["id"] for r in rd.range(10).sort("id", descending=True).take(3)]
    assert desc == [9, 8, 7]
    assert rd.range(100, parallelism=7).repartition(3).materialize() \
        .num_blocks() == 3


def test_union_zip(data_cluster):
    assert rd.range(5).union(rd.range(5), rd.range(5)).count() == 15
    z = rd.range(5).zip(rd.range(5).map_batches(
        lambda b: {"x": b["id"] * 10}))
    assert z.take(2) == [{"id": 0, "x": 0}, {"id": 1, "x": 10}]


def test_actor_pool_udf(data_cluster):
    class AddN:
        def __init__(self, n):
            self.n = n

        def __call__(self, batch):
            return {"id": batch["id"] + self.n}

    ds = rd.range(40, parallelism=4).map_batches(
        AddN, fn_constructor_args=(100,), concurrency=2)
    assert sorted(r["id"] for r in ds.take_all()) == list(range(100, 140))


def test_iter_batches_shapes(data_cluster):
    sizes = [len(b["id"]) for b in rd.range(100, parallelism=4)
             .iter_batches(batch_size=32)]
    assert sizes == [32, 32, 32, 4]
    sizes = [len(b["id"]) for b in rd.range(100, parallelism=4)
             .iter_batches(batch_size=32, drop_last=True)]
    assert sizes == [32, 32, 32]
    df = next(iter(rd.range(8).iter_batches(
        batch_size=8, batch_format="pandas")))
    assert list(df.columns) == ["id"]


def test_iter_jax_batches(data_cluster):
    import jax.numpy as jnp

    got = list(rd.range(32).iter_jax_batches(
        batch_size=16, dtypes={"id": jnp.float32}))
    assert len(got) == 2
    assert got[0]["id"].dtype == jnp.float32
    assert got[0]["id"].shape == (16,)


def test_tensor_blocks(data_cluster):
    ds = rd.range_tensor(16, shape=(2, 3), parallelism=2)
    b = ds.take_batch(4)
    assert b["data"].shape == (4, 2, 3)
    out = ds.map_batches(lambda b: {"data": b["data"] * 2}).take_batch(2)
    assert out["data"].shape == (2, 2, 3)


def test_from_numpy_pandas_arrow(data_cluster):
    import pandas as pd
    import pyarrow as pa

    assert rd.from_numpy(np.ones((5, 2)))._last_op is not None
    assert rd.from_numpy(np.arange(5)).count() == 5
    assert rd.from_pandas(pd.DataFrame({"a": [1, 2]})).count() == 2
    assert rd.from_arrow(pa.table({"a": [1, 2, 3]})).count() == 3
    df = rd.from_pandas(pd.DataFrame({"a": [1, 2]})).to_pandas()
    assert df["a"].tolist() == [1, 2]


def test_parquet_csv_json_roundtrip(data_cluster, tmp_path):
    d = str(tmp_path / "pq")
    rd.range(50, parallelism=2).write_parquet(d)
    assert rd.read_parquet(d).count() == 50
    d = str(tmp_path / "csv")
    rd.range(20).write_csv(d)
    assert rd.read_csv(d).sum("id") == 190
    d = str(tmp_path / "json")
    rd.range(10).write_json(d)
    assert rd.read_json(d).count() == 10


def test_split(data_cluster):
    parts = rd.range(30, parallelism=6).split(3)
    assert [p.count() for p in parts] == [10, 10, 10]
    parts = rd.range(31, parallelism=6).split(3, equal=True)
    assert [p.count() for p in parts] == [10, 10, 10]


def test_split_at_indices_and_proportionately(data_cluster):
    parts = rd.range(20, parallelism=4).split_at_indices([5, 12])
    assert [p.count() for p in parts] == [5, 7, 8]
    rows = [r["id"] for r in parts[1].take_all()]
    assert rows == list(range(5, 12))
    with pytest.raises(ValueError):
        rd.range(10).split_at_indices([7, 3])

    parts = rd.range(100, parallelism=4).split_proportionately([0.2, 0.3])
    assert [p.count() for p in parts] == [20, 30, 50]
    with pytest.raises(ValueError):
        rd.range(10).split_proportionately([0.9, 0.2])


def test_train_test_split(data_cluster):
    train, test = rd.range(50, parallelism=4).train_test_split(0.2)
    assert train.count() == 40 and test.count() == 10
    # unshuffled: test is the tail
    assert [r["id"] for r in test.take_all()] == list(range(40, 50))
    train, test = rd.range(50, parallelism=4).train_test_split(
        10, shuffle=True, seed=7)
    assert train.count() == 40 and test.count() == 10
    ids = sorted(r["id"] for r in train.take_all()) + \
        sorted(r["id"] for r in test.take_all())
    assert sorted(ids) == list(range(50))
    assert [r["id"] for r in test.take_all()] != list(range(40, 50))


def test_unique(data_cluster):
    ds = rd.from_items([{"tag": t} for t in
                        ["a", "b", "a", "c", "b", "a"]])
    assert sorted(ds.unique("tag")) == ["a", "b", "c"]


def test_to_torch(data_cluster):
    torch = pytest.importorskip("torch")
    ds = rd.range(16, parallelism=2)
    it = ds.to_torch(batch_size=4)
    batches = list(iter(it))
    assert len(batches) == 4
    assert all(isinstance(b["id"], torch.Tensor) for b in batches)
    assert int(sum(b["id"].sum() for b in batches)) == sum(range(16))


def test_streaming_split_epochs(data_cluster):
    its = rd.range(24, parallelism=4).streaming_split(2)
    assert sum(len(list(it.iter_rows())) for it in its) == 24
    # a second epoch works (iterators are re-usable)
    assert sum(len(list(it.iter_rows())) for it in its) == 24


def test_random_sample(data_cluster):
    n = rd.range(1000, parallelism=4).random_sample(0.5, seed=0).count()
    assert 350 < n < 650


def test_udf_error_propagates(data_cluster):
    def boom(batch):
        raise ValueError("boom")

    with pytest.raises(Exception, match="boom"):
        rd.range(10).map_batches(boom).take_all()


def test_local_shuffle(data_cluster):
    rows = []
    for b in rd.range(64, parallelism=2).iter_batches(
            batch_size=16, local_shuffle_buffer_size=64,
            local_shuffle_seed=3):
        rows.extend(b["id"].tolist())
    assert sorted(rows) == list(range(64))
    assert rows != list(range(64))


def test_dataset_stats(data_cluster):
    ds = rd.range(100, parallelism=4).map_batches(lambda b: b)
    ds.count()
    assert "tasks" in ds.stats()


def test_train_integration(data_cluster):
    from ray_tpu.train import get_context, get_dataset_shard, report
    from ray_tpu.train.jax import JaxTrainer
    from ray_tpu.air.config import ScalingConfig

    def loop(config):
        it = get_dataset_shard("train")
        total = 0
        for batch in it.iter_batches(batch_size=8):
            total += len(batch["id"])
        report({"rows": total})

    trainer = JaxTrainer(
        loop,
        scaling_config=ScalingConfig(num_workers=2,
                                     resources_per_worker={"CPU": 0.5}),
        datasets={"train": rd.range(64, parallelism=4)},
    )
    result = trainer.fit()
    assert result.metrics["rows"] > 0


def test_map_batches_honors_batch_size(data_cluster):
    def udf(batch):
        # one output row per invocation, recording the batch length
        return {"n": np.array([len(batch["id"])])}

    out = rd.range(100, parallelism=1).map_batches(
        udf, batch_size=32).take_all()
    assert [r["n"] for r in out] == [32, 32, 32, 4]


def test_fusion_preserves_remote_args(data_cluster):
    ds = rd.range(10, parallelism=2).map_batches(
        lambda b: b, num_cpus=0.25)
    from ray_tpu.data._internal.planner import optimize

    ops = optimize(ds._last_op.chain())
    # the resource-carrying map must NOT be fused into the read
    assert len(ops) == 2
    assert ops[1].ray_remote_args == {"num_cpus": 0.25}
    # matching/empty remote args still fuse map->map
    ds2 = rd.range(10, parallelism=2).map(lambda r: r).map(lambda r: r)
    assert len(optimize(ds2._last_op.chain())) == 1


def test_select_drop_rename_block_ops(data_cluster):
    ds = rd.from_items([{"a": i, "b": i * 2, "c": 0} for i in range(8)])
    out = ds.select_columns(["a", "b"]).rename_columns(
        {"b": "bb"}).drop_columns(["a"]).take_all()
    assert list(out[0].keys()) == ["bb"]
    assert [r["bb"] for r in out] == [i * 2 for i in range(8)]


def test_zero_copy_batch_fusion(data_cluster):
    """Consecutive same-format batch transforms pass batches straight
    through without block round-trips (reference: rules/
    zero_copy_map_fusion.py). Observable: a mutation-free chain computes
    correctly AND an identity-checking probe sees the PREVIOUS udf's
    exact output object."""
    import numpy as np

    seen = {}

    def first(b):
        out = {"id": b["id"] * 2}
        seen["obj"] = out["id"]
        return out

    def second(b):
        # same ndarray object arrives — no intermediate block copy
        seen["same"] = b["id"] is seen.get("obj")
        return {"id": b["id"] + 1}

    from ray_tpu.data._internal.logical import MapSpec
    from ray_tpu.data._internal.physical import _apply_specs
    from ray_tpu.data.block import BlockAccessor

    block = BlockAccessor.batch_to_block(
        {"id": np.arange(10, dtype=np.int64)})
    out = _apply_specs(
        [MapSpec(kind="batches", fn=first),
         MapSpec(kind="batches", fn=second)], block)
    rows = BlockAccessor(out).to_batch()
    np.testing.assert_array_equal(rows["id"], np.arange(10) * 2 + 1)
    assert seen["same"] is True
    # and the e2e path still agrees
    ds = rd.range(20, parallelism=2).map_batches(first).map_batches(second)
    assert sorted(r["id"] for r in ds.take_all()) == \
        sorted(2 * i + 1 for i in range(20))


def test_gated_db_datasources(data_cluster):
    """Mongo/BigQuery compose offline and raise clear ImportErrors at
    read time when their clients are absent (reference:
    datasource/mongo_datasource.py, bigquery_datasource.py)."""
    import pytest as _pytest

    def has(mod):
        try:
            __import__(mod)
            return True
        except ImportError:
            return False

    ds = rd.read_mongo("mongodb://localhost:27017", "db", "coll")
    if not has("pymongo"):
        with _pytest.raises(Exception, match="pymongo"):
            ds.take_all()
    bq = rd.read_bigquery("proj", query="SELECT 1 AS x")
    if not has("google.cloud.bigquery"):
        with _pytest.raises(Exception, match="bigquery"):
            bq.take_all()
    with _pytest.raises(ValueError):
        rd.read_bigquery("proj")
