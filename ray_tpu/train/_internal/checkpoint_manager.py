"""Top-K checkpoint retention (reference:
python/ray/train/_internal/checkpoint_manager.py, config
air/config.py:427)."""

from __future__ import annotations

import shutil
from typing import Dict, List, Optional, Tuple

from ray_tpu.air.config import CheckpointConfig
from ray_tpu.train._checkpoint import Checkpoint


class _TrackedCheckpoint:
    def __init__(self, checkpoint: Checkpoint, metrics: Dict, index: int):
        self.checkpoint = checkpoint
        self.metrics = metrics
        self.index = index


class CheckpointManager:
    def __init__(self, config: Optional[CheckpointConfig] = None):
        self.config = config or CheckpointConfig()
        self._checkpoints: List[_TrackedCheckpoint] = []
        self._counter = 0

    def register_checkpoint(self, checkpoint: Checkpoint, metrics: Dict) -> None:
        self._counter += 1
        self._checkpoints.append(
            _TrackedCheckpoint(checkpoint, metrics, self._counter))
        keep = self.config.num_to_keep
        if keep is None or len(self._checkpoints) <= keep:
            return
        attr = self.config.checkpoint_score_attribute
        if attr:
            ranked = sorted(self._checkpoints, key=self._score, reverse=True)
        else:
            ranked = sorted(self._checkpoints, key=lambda t: t.index,
                            reverse=True)
        for dropped in ranked[keep:]:
            self._checkpoints.remove(dropped)
            # scheme-aware: remote checkpoints are deleted through their
            # storage backend, local ones from disk; a failed remote delete
            # must be loud (a silently-leaked bucket prefix grows forever)
            from ray_tpu._private.storage import (
                get_storage_backend, is_remote_uri)

            if is_remote_uri(dropped.checkpoint.path):
                try:
                    get_storage_backend(dropped.checkpoint.path).delete(
                        dropped.checkpoint.path)
                except Exception as e:
                    import logging

                    logging.getLogger(__name__).warning(
                        "failed to prune remote checkpoint %s: %s",
                        dropped.checkpoint.path, e)
            else:
                shutil.rmtree(dropped.checkpoint.path, ignore_errors=True)

    def _score(self, t: _TrackedCheckpoint) -> Tuple:
        """Rank key, higher = better. A checkpoint missing the score
        attribute ranks worst in BOTH orders (leading bool), so min-order
        can't accidentally crown it via -1 * -inf."""
        attr = self.config.checkpoint_score_attribute
        sign = 1 if self.config.checkpoint_score_order == "max" else -1
        val = t.metrics.get(attr)
        return (val is not None, sign * val if val is not None else 0.0,
                t.index)

    @property
    def latest_checkpoint(self) -> Optional[Checkpoint]:
        if not self._checkpoints:
            return None
        return max(self._checkpoints, key=lambda t: t.index).checkpoint

    @property
    def best_checkpoint(self) -> Optional[Checkpoint]:
        if not self._checkpoints:
            return None
        attr = self.config.checkpoint_score_attribute
        if not attr:
            return self.latest_checkpoint
        return max(self._checkpoints, key=self._score).checkpoint

    def best_checkpoints(self) -> List[Tuple[Checkpoint, Dict]]:
        return [(t.checkpoint, t.metrics)
                for t in sorted(self._checkpoints, key=lambda t: t.index)]
