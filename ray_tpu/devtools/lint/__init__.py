"""raylint — AST/CFG invariant checker for the ray_tpu runtime.

Eight rules distilled from the repo's shipped-bug history (each rule
module's docstring names the motivating incident):

- R1  GC-reentrancy: plain ``Lock`` reachable from ``__del__``/weakref
      callbacks (the MemoryStore driver-wide deadlock, PR 5).
- R2  blocking calls inside ``async def`` (event-loop stalls read as
      node death).
- R3  thread lock held across an ``await``.
- R4  fire-and-forget ``create_task``/``ensure_future`` (the leaked
      read-loop tasks, PRs 1/3).
- R5  cross-process exceptions must survive pickle with fields intact.
- R6  control RPCs must carry a timeout/retry budget (the watchdog
      wedge under one-way partitions, PR 5).
- R7  every ``Popen`` registers with the PR 1 pid registry (the daemon
      leaks that starved the MULTICHIP gate).
- R8  ``CONFIG.<flag>`` references must exist in config.py.

Run ``python -m ray_tpu.devtools.lint ray_tpu``; suppress a justified
site inline with ``# raylint: disable=Rn -- reason``; historical debt
lives in ``baseline.json`` which may only shrink. Enforced in tier-1 by
``tests/test_raylint.py``.
"""

from .engine import default_baseline_path, discover_files, run_lint  # noqa: F401
from .model import LintResult, Violation  # noqa: F401
from .rules import rule_catalog  # noqa: F401

__all__ = ["run_lint", "discover_files", "default_baseline_path",
           "LintResult", "Violation", "rule_catalog"]
