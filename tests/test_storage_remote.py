"""Pluggable checkpoint storage (VERDICT r3 missing #2 / next #3): Train
checkpoints upload from the worker process, Tune experiment state mirrors
to the URI, and both restore from a non-local URI.

Reference behavior being matched: pyarrow-fs uploads in
python/ray/train/_internal/storage.py:99-111. Here the scheme resolves a
StorageBackend (ray_tpu/_private/storage.py); mock:// is the in-tree fake
object store (object semantics, no os.path access from consumers)."""

import os
import uuid

import pytest

import ray_tpu
from ray_tpu._private.storage import (
    FakeRemoteBackend, get_storage_backend, is_remote_uri, join_uri,
    parse_uri)
from ray_tpu.air.config import RunConfig, ScalingConfig
from ray_tpu.train import Checkpoint, JaxTrainer
from ray_tpu.train.jax import JaxConfig


@pytest.fixture(scope="module")
def cluster():
    ray_tpu.init(num_cpus=4, ignore_reinit_error=True)
    yield ray_tpu
    ray_tpu.shutdown()


@pytest.fixture
def bucket():
    uri = f"mock://bucket-{uuid.uuid4().hex[:8]}"
    yield uri
    get_storage_backend(uri).delete(uri)


# ---------------------------------------------------------------- unit layer
def test_uri_helpers():
    assert parse_uri("gs://b/k") == ("gs", "b/k")
    assert parse_uri("/x/y") == (None, "/x/y")
    assert parse_uri("file:///x") == ("file", "/x")
    assert is_remote_uri("gs://b") and is_remote_uri("mock://b")
    assert not is_remote_uri("/tmp/x") and not is_remote_uri("file:///x")
    assert join_uri("mock://b/", "e", "t") == "mock://b/e/t"


def test_fake_backend_roundtrip(tmp_path, bucket):
    b = get_storage_backend(bucket)
    assert isinstance(b, FakeRemoteBackend)
    src = tmp_path / "src"
    src.mkdir()
    (src / "a.txt").write_text("hello")
    (src / "sub").mkdir()
    (src / "sub" / "b.bin").write_bytes(b"\x00\x01")
    dest = join_uri(bucket, "ckpt_000001")
    b.upload_dir(str(src), dest)
    assert b.exists(dest)
    assert b.listdir(bucket) == ["ckpt_000001"]
    out = tmp_path / "out"
    b.download_dir(dest, str(out))
    assert (out / "a.txt").read_text() == "hello"
    assert (out / "sub" / "b.bin").read_bytes() == b"\x00\x01"
    b.write_bytes(join_uri(bucket, "state.json"), b"{}")
    assert b.read_bytes(join_uri(bucket, "state.json")) == b"{}"
    b.delete(dest)
    assert not b.exists(dest)


def test_unknown_scheme_error_names_register_hook():
    with pytest.raises(RuntimeError, match="register_storage_backend"):
        get_storage_backend("weird-scheme-xyz://bucket")


def test_checkpoint_uri_download(tmp_path, bucket):
    src = tmp_path / "src"
    src.mkdir()
    (src / "w.txt").write_text("42")
    uri = join_uri(bucket, "c0")
    get_storage_backend(uri).upload_dir(str(src), uri)
    ck = Checkpoint(uri)
    assert ck.is_remote and ck.path == uri
    with ck.as_directory() as d:
        assert open(os.path.join(d, "w.txt")).read() == "42"
    assert not os.path.exists(d)  # temp download cleaned up


# ------------------------------------------------------------- train e2e
def _ckpt_train_loop(config):
    import json
    import tempfile

    from ray_tpu import train

    start = 0
    ck = train.get_checkpoint()
    if ck is not None:
        with ck.as_directory() as d:  # remote: downloads in the WORKER
            with open(os.path.join(d, "state.json")) as f:
                start = json.load(f)["step"] + 1
    for i in range(start, config["steps"]):
        d = tempfile.mkdtemp(prefix="ck_")
        with open(os.path.join(d, "state.json"), "w") as f:
            json.dump({"step": i}, f)
        train.report({"step": i, "resumed_from": start},
                     checkpoint=train.Checkpoint(d))


def test_jax_trainer_checkpoints_to_remote_uri_and_resumes(cluster, bucket):
    run1 = JaxTrainer(
        _ckpt_train_loop, train_loop_config={"steps": 3},
        scaling_config=ScalingConfig(num_workers=1,
                                     resources_per_worker={"CPU": 1}),
        jax_config=JaxConfig(),
        run_config=RunConfig(storage_path=bucket, name="exp"),
    ).fit()
    assert run1.error is None, run1.error
    assert run1.metrics["step"] == 2
    ck = run1.checkpoint
    assert ck is not None and ck.is_remote
    assert ck.path.startswith(bucket)
    # the checkpoint really lives in the (fake) bucket, uploaded from the
    # worker process — the driver never copied it
    backend = get_storage_backend(ck.path)
    assert backend.exists(ck.path)
    assert "checkpoint_000002" in backend.listdir(join_uri(bucket, "exp"))

    run2 = JaxTrainer(
        _ckpt_train_loop, train_loop_config={"steps": 5},
        scaling_config=ScalingConfig(num_workers=1,
                                     resources_per_worker={"CPU": 1}),
        jax_config=JaxConfig(),
        run_config=RunConfig(storage_path=bucket, name="exp2"),
        resume_from_checkpoint=ck,
    ).fit()
    assert run2.error is None, run2.error
    assert run2.metrics["resumed_from"] == 3  # resumed, not restarted
    assert run2.metrics["step"] == 4


# -------------------------------------------------------------- tune e2e
def test_tuner_remote_storage_and_restore(cluster, bucket, tmp_path,
                                          monkeypatch):
    monkeypatch.setenv("RAY_TPU_EXPERIMENT_CACHE", str(tmp_path / "cache1"))
    from ray_tpu import tune
    from ray_tpu.tune import Tuner
    from ray_tpu.tune.tuner import TuneConfig

    def objective(config):
        for i in range(3):
            tune.report({"score": config["x"] * (i + 1)})

    grid = Tuner(
        objective,
        param_space={"x": tune.grid_search([1, 2])},
        tune_config=TuneConfig(metric="score", mode="max"),
        run_config=RunConfig(storage_path=bucket, name="sweep"),
    ).fit()
    assert len(grid) == 2
    assert grid.get_best_result().metrics["score"] == 6
    # experiment state mirrored to the bucket
    backend = get_storage_backend(bucket)
    exp_uri = join_uri(bucket, "sweep")
    assert backend.exists(join_uri(exp_uri, "experiment_state.json"))
    assert Tuner.can_restore(exp_uri)

    # restore FROM THE URI into a fresh local cache (simulating a new
    # driver host) and finish without error
    monkeypatch.setenv("RAY_TPU_EXPERIMENT_CACHE", str(tmp_path / "cache2"))
    restored = Tuner.restore(exp_uri, objective).fit()
    assert len(restored) == 2
    assert restored.get_best_result().metrics["score"] == 6
