"""TransformersTrainer + gated GBDT trainers (reference:
train/huggingface/transformers tests + gbdt_trainer tests)."""

import numpy as np
import pytest

import ray_tpu
from ray_tpu.air import ScalingConfig


@pytest.fixture(scope="module")
def ray4():
    if not ray_tpu.is_initialized():
        ray_tpu.init(num_cpus=4)
    yield
    ray_tpu.shutdown()


def test_transformers_trainer_tiny(ray4):
    """Tiny random-weight transformer fine-tune: metrics must flow from HF
    Trainer logs through session.report back to the driver."""
    from ray_tpu.train.huggingface import TransformersTrainer

    def loop(config):
        import torch
        import transformers

        from ray_tpu.train.huggingface import prepare_trainer

        cfg = transformers.GPT2Config(
            n_layer=1, n_head=2, n_embd=32, vocab_size=128,
            n_positions=32)
        model = transformers.GPT2LMHeadModel(cfg)

        class DS(torch.utils.data.Dataset):
            def __len__(self):
                return 16

            def __getitem__(self, i):
                ids = torch.randint(0, 128, (16,))
                return {"input_ids": ids, "labels": ids}

        args = transformers.TrainingArguments(
            output_dir="/tmp/hf_out", num_train_epochs=1,
            per_device_train_batch_size=4, logging_steps=1,
            report_to=[], max_steps=3, use_cpu=True,
            disable_tqdm=True)
        trainer = transformers.Trainer(model=model, args=args,
                                       train_dataset=DS())
        trainer = prepare_trainer(trainer)
        trainer.train()

    t = TransformersTrainer(
        loop, scaling_config=ScalingConfig(num_workers=1))
    result = t.fit()
    assert result.error is None
    assert result.metrics is not None
    assert "loss" in result.metrics or "train_loss" in result.metrics


def test_accelerate_trainer_tiny(ray4):
    """Tiny model trained through accelerate.Accelerator on one worker."""
    from ray_tpu.train.accelerate import AccelerateTrainer

    def loop(config):
        import torch
        from accelerate import Accelerator

        from ray_tpu import train

        acc = Accelerator(cpu=True)
        model = torch.nn.Linear(4, 1)
        opt = torch.optim.SGD(model.parameters(), lr=0.1)
        model, opt = acc.prepare(model, opt)
        x = torch.randn(64, 4)
        y = x.sum(dim=1, keepdim=True)
        for _ in range(10):
            loss = torch.nn.functional.mse_loss(model(x), y)
            acc.backward(loss)
            opt.step()
            opt.zero_grad()
        train.report({"loss": float(loss)})

    result = AccelerateTrainer(
        loop, scaling_config=ScalingConfig(num_workers=1)).fit()
    assert result.error is None
    assert result.metrics["loss"] < 5.0


def test_lightning_trainer_gated():
    from ray_tpu.train import LightningTrainer

    try:
        import lightning  # noqa: F401
        pytest.skip("lightning installed; gate not applicable")
    except ImportError:
        pass
    with pytest.raises(ImportError, match="lightning"):
        LightningTrainer(lambda c: None)


def test_gbdt_trainers_gated():
    from ray_tpu.train import LightGBMTrainer, XGBoostTrainer

    def has(lib):
        try:
            __import__(lib)
            return True
        except ImportError:
            return False

    if not has("xgboost"):
        with pytest.raises(ImportError, match="xgboost"):
            XGBoostTrainer(datasets={}, label_column="y")
    if not has("lightgbm"):
        with pytest.raises(ImportError, match="lightgbm"):
            LightGBMTrainer(datasets={}, label_column="y")
