"""RLModule — the model abstraction (reference:
rllib/core/rl_module/rl_module.py; the reference's torch/tf modules become
pure-JAX functional modules here: params are a pytree, forward is a pure
function, so the same module runs jitted on TPU in the Learner and on CPU in
env runners).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np


# ------------------------------------------------------------ distributions
class Categorical:
    """Action distribution over discrete logits (reference:
    rllib/models/distributions torch Categorical analog)."""

    @staticmethod
    def sample(rng, logits):
        return jax.random.categorical(rng, logits, axis=-1)

    @staticmethod
    def logp(logits, actions):
        logps = jax.nn.log_softmax(logits, axis=-1)
        return jnp.take_along_axis(
            logps, actions[..., None].astype(jnp.int32), axis=-1)[..., 0]

    @staticmethod
    def entropy(logits):
        logps = jax.nn.log_softmax(logits, axis=-1)
        return -jnp.sum(jnp.exp(logps) * logps, axis=-1)


class DiagGaussian:
    """Squash-free diagonal Gaussian for continuous actions; logits =
    concat(mean, log_std)."""

    @staticmethod
    def split(logits):
        mean, log_std = jnp.split(logits, 2, axis=-1)
        return mean, jnp.clip(log_std, -20.0, 2.0)

    @staticmethod
    def sample(rng, logits):
        mean, log_std = DiagGaussian.split(logits)
        return mean + jnp.exp(log_std) * jax.random.normal(rng, mean.shape)

    @staticmethod
    def logp(logits, actions):
        mean, log_std = DiagGaussian.split(logits)
        var = jnp.exp(2 * log_std)
        return jnp.sum(
            -0.5 * ((actions - mean) ** 2 / var)
            - log_std - 0.5 * jnp.log(2 * jnp.pi), axis=-1)

    @staticmethod
    def entropy(logits):
        _, log_std = DiagGaussian.split(logits)
        return jnp.sum(log_std + 0.5 * jnp.log(2 * jnp.pi * jnp.e), axis=-1)


# ----------------------------------------------------------------- RLModule
@dataclasses.dataclass
class RLModuleSpec:
    """Reference: rllib/core/rl_module/rl_module.py RLModuleSpec."""

    obs_dim: int
    action_dim: int
    discrete: bool = True
    hiddens: Tuple[int, ...] = (64, 64)
    activation: str = "tanh"
    # catalog extensions (reference: models/catalog.py):
    obs_shape: Optional[Tuple[int, ...]] = None  # (H, W, C) -> ConvModule
    conv_filters: Optional[Tuple] = None         # ((out, kernel, stride),)
    use_lstm: bool = False                       # -> LSTMModule
    lstm_cell_size: int = 64

    def build(self):
        from ray_tpu.rllib.models.catalog import get_module_for_space

        return get_module_for_space(self)


class MLPModule:
    """Separate policy/value MLP towers (reference default model:
    rllib/models/catalog.py fcnet)."""

    def __init__(self, spec: RLModuleSpec):
        self.spec = spec
        self.dist = Categorical if spec.discrete else DiagGaussian
        self._act = {"tanh": jnp.tanh, "relu": jax.nn.relu}[spec.activation]
        self._out_dim = (spec.action_dim if spec.discrete
                         else 2 * spec.action_dim)

    # ------------------------------------------------------------- params
    def init(self, rng) -> Dict:
        def mlp_params(key, sizes):
            layers = []
            for i, (a, b) in enumerate(zip(sizes[:-1], sizes[1:])):
                key, sub = jax.random.split(key)
                scale = jnp.sqrt(2.0 / a)
                # tiny final layer: near-uniform initial policy
                if i == len(sizes) - 2:
                    scale = scale * 0.01
                layers.append({
                    "w": jax.random.normal(sub, (a, b)) * scale,
                    "b": jnp.zeros((b,)),
                })
            return layers

        k1, k2 = jax.random.split(rng)
        sizes = (self.spec.obs_dim, *self.spec.hiddens)
        return {
            "pi": mlp_params(k1, sizes + (self._out_dim,)),
            "vf": mlp_params(k2, sizes + (1,)),
        }

    # ------------------------------------------------------------ forward
    def _tower(self, layers, x):
        for layer in layers[:-1]:
            x = self._act(x @ layer["w"] + layer["b"])
        last = layers[-1]
        return x @ last["w"] + last["b"]

    def forward(self, params, obs) -> Dict[str, jnp.ndarray]:
        """Returns action logits and value estimate."""
        logits = self._tower(params["pi"], obs)
        vf = self._tower(params["vf"], obs)[..., 0]
        return {"logits": logits, "vf": vf}

    def explore_action(self, params, obs, rng):
        """Sample action + logp + value — the env-runner inference path."""
        out = self.forward(params, obs)
        action = self.dist.sample(rng, out["logits"])
        logp = self.dist.logp(out["logits"], action)
        return action, logp, out["vf"]
