"""ASGI ingress (reference: python/ray/serve/api.py:170 @serve.ingress —
wraps a deployment class so an ASGI app (FastAPI or any ASGI3 callable)
serves its HTTP traffic; reference's ASGIAppReplicaWrapper in
_private/http_util.py drives the app with starlette's protocol).

No uvicorn/starlette in this image: the proxy parses HTTP itself and hands
replicas a ``Request``; this module translates that into an ASGI scope,
drives the app, and returns a ``Response`` (or streams body chunks).
"""

from __future__ import annotations

import inspect
from typing import Any, AsyncIterator, Callable, Dict, Iterable, Optional

__all__ = ["ingress", "Response", "StreamingResponse"]


class Response:
    """Explicit HTTP response from a deployment (starlette.Response analog):
    carries status/headers/body through the handle back to the proxy."""

    def __init__(self, content: Any = b"", status_code: int = 200,
                 headers: Optional[Dict[str, str]] = None,
                 media_type: Optional[str] = None):
        self.status_code = status_code
        self.headers = dict(headers or {})
        if isinstance(content, bytes):
            self.body = content
            default_type = "application/octet-stream"
        elif isinstance(content, str):
            self.body = content.encode()
            default_type = "text/plain"
        else:
            import json

            self.body = json.dumps(content, default=str).encode()
            default_type = "application/json"
        self.media_type = media_type or default_type

    def __reduce__(self):
        r = Response.__new__(Response)
        state = {"status_code": self.status_code, "headers": self.headers,
                 "body": self.body, "media_type": self.media_type}
        return (_rebuild_response, (state,))


def _rebuild_response(state: Dict) -> "Response":
    r = Response.__new__(Response)
    r.__dict__.update(state)
    return r


class StreamingResponse:
    """Chunked-transfer response: wraps a (sync or async) iterator of
    str/bytes chunks (reference: starlette StreamingResponse served through
    replica.py:471's streaming path)."""

    def __init__(self, content: Iterable, status_code: int = 200,
                 media_type: str = "application/octet-stream",
                 headers: Optional[Dict[str, str]] = None):
        self.content = content
        self.status_code = status_code
        self.media_type = media_type
        self.headers = dict(headers or {})


async def _run_asgi(app: Callable, request) -> Response:
    """Drive one request through an ASGI3 app, buffering the response."""
    query = "&".join(f"{k}={v}" for k, v in request.query_params.items())
    scope = {
        "type": "http",
        "asgi": {"version": "3.0", "spec_version": "2.3"},
        "http_version": "1.1",
        "method": request.method.upper(),
        "scheme": "http",
        "path": request.path,
        "raw_path": request.path.encode(),
        "root_path": "",
        "query_string": query.encode(),
        "headers": [(k.lower().encode(), v.encode())
                    for k, v in request.headers.items()],
        "client": ("127.0.0.1", 0),
        "server": ("127.0.0.1", 0),
    }
    body = request.body or b""
    sent_body = False

    async def receive():
        nonlocal sent_body
        if not sent_body:
            sent_body = True
            return {"type": "http.request", "body": body, "more_body": False}
        return {"type": "http.disconnect"}

    status = 500
    headers: Dict[str, str] = {}
    chunks = []

    async def send(message):
        nonlocal status, headers
        if message["type"] == "http.response.start":
            status = message["status"]
            headers = {k.decode(): v.decode()
                       for k, v in message.get("headers", [])}
        elif message["type"] == "http.response.body":
            chunks.append(message.get("body", b""))

    await app(scope, receive, send)
    media_type = headers.pop("content-type", None)
    return Response(b"".join(chunks), status_code=status, headers=headers,
                    media_type=media_type)


def _bind_fastapi_routes(app, instance) -> None:
    """FastAPI class-based views: route endpoints defined as methods of the
    ingress class captured the UNBOUND function at decoration time; rebind
    them to the replica's instance (reference:
    _private/http_util.py make_fastapi_class_based_view)."""
    try:
        routes = app.routes
    except AttributeError:
        return
    cls = type(instance)
    for route in routes:
        endpoint = getattr(route, "endpoint", None)
        if endpoint is None:
            continue
        for name, member in inspect.getmembers(cls):
            if member is endpoint or getattr(member, "__func__", None) is endpoint:
                bound = getattr(instance, name)
                route.endpoint = bound
                # FastAPI resolves the handler through the dependant graph
                dependant = getattr(route, "dependant", None)
                if dependant is not None:
                    dependant.call = bound
                break


def ingress(app_or_func: Callable):
    """Class decorator: route all HTTP traffic for this deployment through
    an ASGI app. ``@serve.deployment`` + ``@serve.ingress(asgi_app)``.

    Works with any ASGI3 callable (FastAPI instances included); with
    FastAPI, endpoint methods defined on the decorated class are rebound to
    the replica instance at construction.
    """
    asgi_app = app_or_func

    def decorator(cls):
        if not isinstance(cls, type):
            raise TypeError("@serve.ingress decorates a class; for plain "
                            "functions use @serve.deployment directly")

        class ASGIIngress(cls):
            def __init__(self, *args, **kwargs):
                super().__init__(*args, **kwargs)
                _bind_fastapi_routes(asgi_app, self)
                self.__asgi_app = asgi_app

            async def __call__(self, request):
                return await _run_asgi(asgi_app, request)

        ASGIIngress.__name__ = cls.__name__
        ASGIIngress.__qualname__ = cls.__qualname__
        ASGIIngress.__module__ = cls.__module__
        ASGIIngress.__serve_asgi_ingress__ = True
        return ASGIIngress

    return decorator


def iterate_sync(content) -> Iterable:
    """Normalize StreamingResponse content / generators to a sync iterator
    (async generators are drained on a private event loop)."""
    if hasattr(content, "__aiter__"):
        import asyncio

        agen: AsyncIterator = content.__aiter__()
        loop = asyncio.new_event_loop()
        try:
            while True:
                try:
                    yield loop.run_until_complete(agen.__anext__())
                except StopAsyncIteration:
                    return
        finally:
            loop.close()
    else:
        yield from content
