from ray_tpu.train.torch.config import TorchConfig
from ray_tpu.train.torch.torch_trainer import TorchTrainer
from ray_tpu.train.torch.train_loop_utils import (
    prepare_data_loader,
    prepare_model,
)

__all__ = ["TorchConfig", "TorchTrainer", "prepare_model",
           "prepare_data_loader"]
