"""Cross-slice (DCN) multi-process gate (VERDICT r4 #4): two separate
jax.distributed process groups of 2 devices each form a (dcn=2, ici=2)
mesh — the outer axis spans slices — and the workers assert the
hierarchical reduction: per-slice ICI psum partials [3, 7] then the
cross-slice DCN allreduce total 10 (a value only a real global mesh can
produce), plus a data-parallel train step whose gradient is reduced
ICI-first then DCN and matches the single-host computation.

Reference analog: multi-slice data parallelism over DCN
(jax.experimental.multihost_utils semantics; SURVEY §5 'Distributed
communication backend', §7 Phase 3 v5e-multi-slice shape).
"""

import os
import sys

import pytest

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.mark.timeout(900)
def test_two_slice_hierarchical_psum_and_grad_step():
    sys.path.insert(0, _REPO_ROOT)
    import __graft_entry__ as ge

    outs = ge._spawn_entry_workers("--two-slice-worker", 2)
    for rank, out in enumerate(outs):
        assert f"two-slice-worker rank={rank}" in out and "ok" in out, out
        # the per-slice ICI partials and the DCN total are printed by each
        # worker; check the asserted values made it through
        assert "partials=[3.0, 7.0]" in out, out
        assert "total=10.0" in out, out
