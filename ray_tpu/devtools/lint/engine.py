"""Lint engine: file discovery, parsing, rule driving, suppression.

Two rule shapes are supported (see rules/__init__.py): per-module
``check_module(mod, index)`` and project-wide ``check(index)`` (for
rules that need the cross-module call graph or the config flag table).
Inline ``# raylint: disable=Rn`` comments suppress at the site; the
baseline manager grandfathers historical debt; everything else fails.
"""

from __future__ import annotations

import ast
import os
import time
from typing import Dict, Iterable, List, Optional

from . import baseline as baseline_mod
from .callgraph import ProjectIndex
from .model import LintResult, ModuleInfo, Violation
from .rules import ALL_RULES, RULES_BY_ID

_SKIP_DIRS = {"__pycache__", ".git", "node_modules"}


def discover_files(paths: Iterable[str]) -> List[str]:
    out: List[str] = []
    for p in paths:
        if os.path.isfile(p) and p.endswith(".py"):
            out.append(p)
        elif os.path.isdir(p):
            for root, dirs, files in os.walk(p):
                dirs[:] = sorted(d for d in dirs if d not in _SKIP_DIRS)
                for f in sorted(files):
                    if f.endswith(".py"):
                        out.append(os.path.join(root, f))
    return out


def parse_modules(files: List[str], project_root: str
                  ) -> (List[ModuleInfo], List[str]):
    mods: List[ModuleInfo] = []
    errors: List[str] = []
    for path in files:
        try:
            with open(path, "r", encoding="utf-8") as f:
                source = f.read()
            tree = ast.parse(source, filename=path)
        except (SyntaxError, UnicodeDecodeError, OSError) as e:
            errors.append(f"{path}: {e}")
            continue
        rel = os.path.relpath(os.path.abspath(path),
                              os.path.abspath(project_root))
        mods.append(ModuleInfo(path, rel.replace(os.sep, "/"), source, tree))
    return mods, errors


def run_lint(paths: Iterable[str],
             project_root: Optional[str] = None,
             rules: Optional[List[str]] = None,
             baseline_path: Optional[str] = None,
             report_only: Optional[Iterable[str]] = None) -> LintResult:
    """Run the analyzer; returns a LintResult with failing /
    grandfathered / suppressed violations split out.

    ``baseline_path=None`` means no baseline (every unsuppressed
    violation fails); pass the checked-in file for the tier-1 contract.
    ``report_only`` restricts *reported* violations to those
    project-relative paths while the index (and therefore call-graph
    precision) still covers everything in ``paths`` — the ``--changed``
    mode: lint the diff against the full-tree index.
    """
    t0 = time.monotonic()
    project_root = project_root or os.getcwd()
    files = discover_files(paths)
    mods, errors = parse_modules(files, project_root)
    index = ProjectIndex(mods)
    # rules that consult files outside the module set (R8's README knob
    # tables) anchor themselves here
    index.project_root = os.path.abspath(project_root)

    selected = ALL_RULES if not rules else [
        RULES_BY_ID[r.upper()] for r in rules]

    raw: List[Violation] = []
    for rule in selected:
        if hasattr(rule, "check"):
            raw.extend(rule.check(index))
        if hasattr(rule, "check_module"):
            for mod in mods:
                raw.extend(rule.check_module(mod, index))
    raw.sort(key=lambda v: (v.path, v.line, v.rule))

    if report_only is not None:
        keep = {p.replace(os.sep, "/") for p in report_only}
        raw = [v for v in raw if v.path in keep]

    by_mod: Dict[str, ModuleInfo] = {m.relpath: m for m in mods}
    unsuppressed: List[Violation] = []
    suppressed = 0
    for v in raw:
        mod = by_mod.get(v.path)
        if mod is not None and mod.is_disabled(v.rule, v.line):
            suppressed += 1
        else:
            unsuppressed.append(v)

    bl = baseline_mod.load(baseline_path) if baseline_path else {}
    failing, grandfathered, stale = baseline_mod.split(unsuppressed, bl)
    if report_only is not None:
        stale = []  # a partial report can't prove baseline entries stale

    result = LintResult(
        violations=failing,
        grandfathered=grandfathered,
        suppressed_count=suppressed,
        stale_baseline=stale,
        files_scanned=len(mods),
        parse_errors=errors,
        elapsed_s=time.monotonic() - t0,
    )
    result._index = index  # CLI extras (--dump-lock-graph) reuse it
    return result


def default_baseline_path() -> str:
    return os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "baseline.json")
