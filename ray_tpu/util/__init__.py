"""ray_tpu.util — utilities (reference: python/ray/util/ — ActorPool
actor_pool.py, Queue queue.py, metrics metrics.py, state api, collective,
placement groups, scheduling strategies)."""

from ray_tpu.util.actor_pool import ActorPool
from ray_tpu.util.queue import Queue

__all__ = ["ActorPool", "Queue"]
