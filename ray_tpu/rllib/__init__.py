"""ray_tpu.rllib — RL at scale, JAX-native (reference: rllib/ —
Algorithm algorithms/algorithm.py:193, new-stack Learner
core/learner/learner.py:105, EnvRunner env/env_runner.py:15; SURVEY §2.4
RLlib row, §7 phase 7).

The reference's ``framework='torch'/'tf2'`` stacks are replaced by a single
JAX stack: RLModules are pure-function params+apply, Learners jit their
update over the device mesh (GSPMD psum = DDP allreduce), env runners stay
CPU actors.
"""

from ray_tpu.rllib.algorithms import Algorithm, AlgorithmConfig
from ray_tpu.rllib.algorithms.a2c import A2C, A2CConfig
from ray_tpu.rllib.algorithms.appo import APPO, APPOConfig
from ray_tpu.rllib.algorithms.ddppo import DDPPO, DDPPOConfig
from ray_tpu.rllib.algorithms.apex import ApexDQN, ApexDQNConfig
from ray_tpu.rllib.algorithms.qmix import QMIX, QMIXConfig
from ray_tpu.rllib.algorithms.alpha_zero import (
    AlphaZero, AlphaZeroConfig)
from ray_tpu.rllib.algorithms.ars import ARS, ARSConfig
from ray_tpu.rllib.algorithms.bc import BC, BCConfig
from ray_tpu.rllib.algorithms.cql import CQL, CQLConfig
from ray_tpu.rllib.algorithms.crr import CRR, CRRConfig
from ray_tpu.rllib.algorithms.ddpg import DDPG, DDPGConfig, TD3, TD3Config
from ray_tpu.rllib.algorithms.dqn import DQN, DQNConfig
from ray_tpu.rllib.algorithms.dreamerv3 import DreamerV3, DreamerV3Config
from ray_tpu.rllib.algorithms.es import ES, ESConfig
from ray_tpu.rllib.algorithms.impala import IMPALA, IMPALAConfig
from ray_tpu.rllib.algorithms.marwil import MARWIL, MARWILConfig
from ray_tpu.rllib.algorithms.ppo import (
    MultiAgentPPO, MultiAgentPPOConfig, PPO, PPOConfig)
from ray_tpu.rllib.algorithms.bandit import (
    BanditLinTS, BanditLinTSConfig, BanditLinUCB, BanditLinUCBConfig)
from ray_tpu.rllib.algorithms.maddpg import MADDPG, MADDPGConfig
from ray_tpu.rllib.algorithms.r2d2 import R2D2, R2D2Config
from ray_tpu.rllib.algorithms.sac import SAC, SACConfig
from ray_tpu.rllib.core.learner import Learner, PPOLearner
from ray_tpu.rllib.core.learner_group import LearnerGroup
from ray_tpu.rllib.core.rl_module import MLPModule, RLModuleSpec
from ray_tpu.rllib.env.env_runner import SingleAgentEnvRunner
from ray_tpu.rllib.env.multi_agent_env import (
    MultiAgentEnv, MultiAgentEnvRunner)
from ray_tpu.rllib.env.policy_client import PolicyClient
from ray_tpu.rllib.env.policy_server_input import PolicyServerInput

__all__ = [
    "Algorithm", "AlgorithmConfig", "PPO", "PPOConfig", "DDPPO", "QMIX",
    "QMIXConfig", "ApexDQN", "ApexDQNConfig",
    "DDPPOConfig", "DQN", "DQNConfig",
    "BC", "BCConfig", "A2C", "A2CConfig", "APPO", "APPOConfig",
    "CQL", "CQLConfig", "DDPG", "DDPGConfig", "TD3", "TD3Config",
    "ES", "ESConfig", "ARS", "ARSConfig", "MARWIL", "MARWILConfig",
    "AlphaZero", "AlphaZeroConfig", "CRR", "CRRConfig",
    "DreamerV3", "DreamerV3Config",
    "SAC", "SACConfig", "IMPALA", "IMPALAConfig", "Learner",
    "PPOLearner", "LearnerGroup", "MLPModule", "RLModuleSpec",
    "SingleAgentEnvRunner", "MultiAgentEnv", "MultiAgentEnvRunner",
    "MultiAgentPPO", "MultiAgentPPOConfig", "R2D2", "R2D2Config",
    "MADDPG", "MADDPGConfig", "BanditLinUCB", "BanditLinUCBConfig",
    "BanditLinTS", "BanditLinTSConfig",
    "PolicyClient", "PolicyServerInput",
]
